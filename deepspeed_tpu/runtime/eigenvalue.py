"""Hessian max-eigenvalue estimation — reference ``runtime/eigenvalue.py``.

The reference runs power iteration on each layer block's loss Hessian
(via double backward) and feeds the per-layer eigenvalues to
compression's quantization-offset scheduling (``engine.py`` eigenvalue
hooks): layers with a sharper loss surface get gentler quantization.

The JAX version is the natural functional form: a Hessian-vector product
is ``jvp`` through ``grad`` (no double-backward graph bookkeeping), jitted
once and reused across iterations.  Eigenvalues are computed per top-level
parameter block (the layer granularity the reference's module walk
produces).
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


class Eigenvalue:

    def __init__(self, verbose=False, max_iter=100, tol=1e-2,
                 stability=1e-6, gas_boundary_resolution=1,
                 layer_name="", layer_num=0):
        self.verbose = verbose
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.stability = float(stability)
        self.gas_boundary_resolution = int(gas_boundary_resolution)
        self.layer_name = layer_name
        self.layer_num = layer_num
        log_dist(
            f"enabled eigenvalue: max_iter={max_iter} tol={tol} "
            f"stability={stability}", ranks=[0])

    # ------------------------------------------------------------ internals
    @staticmethod
    def _tree_dot(a, b):
        return sum(jnp.vdot(x, y) for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)))

    @staticmethod
    def _tree_norm(a):
        return jnp.sqrt(sum(jnp.vdot(x, x).real for x in
                            jax.tree_util.tree_leaves(a)))

    def _hvp_fn(self, loss_fn, params, inputs):
        """Jitted Hessian-vector product v ↦ ∇²L(params)·v."""
        grad_fn = jax.grad(lambda p: loss_fn(p, *inputs))

        @jax.jit
        def hvp(v):
            return jax.jvp(grad_fn, (params, ), (v, ))[1]

        return hvp

    def _power_iterate(self, hvp, like, key):
        # tangents must match the primal dtype (bf16 params → bf16 tangents)
        v = jax.tree_util.tree_map(
            lambda x: jax.random.normal(key, x.shape, x.dtype)
            if x.size else jnp.zeros_like(x), like)
        norm = self._tree_norm(v)
        v = jax.tree_util.tree_map(lambda x: x / (norm + self.stability), v)
        eig = 0.0
        for _ in range(self.max_iter):
            hv = hvp(v)
            hv = jax.tree_util.tree_map(
                lambda x: jnp.nan_to_num(x, nan=0.0, posinf=0.0,
                                         neginf=0.0), hv)
            new_eig = float(np.real(self._tree_dot(v, hv)))
            norm = self._tree_norm(hv)
            v = jax.tree_util.tree_map(
                lambda x: x / (norm + self.stability), hv)
            if abs(new_eig) < 1e-12:
                return 0.0
            if abs(new_eig - eig) / (abs(new_eig)) < self.tol:
                return new_eig
            eig = new_eig
        return eig

    # --------------------------------------------------------------- public
    def compute_eigenvalue(self, loss_fn, params, *inputs, seed=0):
        """Per-top-level-block max |eigenvalue| of the loss Hessian.

        ``loss_fn(params, *inputs) -> scalar``.  Returns
        ``{block_name: eigenvalue}`` plus ``"__all__"`` for the whole tree
        (the reference returns the per-layer list its module walk found).
        """
        key = jax.random.PRNGKey(seed)
        results = {}
        if isinstance(params, dict):
            for i, name in enumerate(params):
                # restrict differentiation to this block: the HVP costs a
                # block's worth of tangents, not the full tree's
                def loss_block(pb, name=name):
                    return loss_fn({**params, name: pb}, *inputs)

                gfn = jax.grad(loss_block)
                block_hvp = jax.jit(
                    lambda v, gfn=gfn, name=name: jax.jvp(
                        gfn, (params[name], ), (v, ))[1])
                results[name] = self._power_iterate(
                    block_hvp, params[name], jax.random.fold_in(key, i))
                if self.verbose:
                    log_dist(f"eigenvalue[{name}] = {results[name]:.4e}",
                             ranks=[0])
        hvp = self._hvp_fn(loss_fn, params, inputs)
        results["__all__"] = self._power_iterate(hvp, params, key)
        return results
