"""Data loader — analog of reference ``runtime/dataloader.py``
(``DeepSpeedDataLoader``) + ``engine.py:1753 deepspeed_io``.

Single-controller difference: the reference gives each of the N processes a
``DistributedSampler`` shard of the dataset; here one process forms the
*global* batch (micro_batch × dp) and the engine device_puts it sharded over
the dp axis — the per-chip slice is what lands in each chip's HBM, so the
memory/behavior is the same, without the sampler rank bookkeeping.
"""

import math

import numpy as np

import jax


def _to_numpy(x):
    if isinstance(x, np.ndarray):
        return x
    try:
        import torch
        if isinstance(x, torch.Tensor):
            return x.detach().cpu().numpy()
    except ImportError:
        pass
    return np.asarray(x)


def default_collate(samples):
    """Stack a list of samples (each a tuple/list/dict/array) into batch arrays."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples])
                           for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    arrs = [_to_numpy(s) for s in samples]
    return np.stack(arrs)


class DeepSpeedDataLoader:
    """Iterates a map-style dataset in global batches.

    ``batch_size`` here is the *global* effective micro batch
    (micro_batch_per_gpu × dp_world_size), matching what the engine shards.

    ``num_local_io_workers`` (reference ``deepspeed_io`` engine.py:1753 /
    torch DataLoader ``num_workers`` role): > 0 assembles upcoming batches
    on a thread pool with a sliding window of ``workers + 1`` in flight, so
    dataset ``__getitem__`` IO (e.g. ``indexed_dataset`` mmap reads) and
    collation overlap the device step instead of serializing with it.
    Ordering is preserved either way.
    """

    def __init__(self, dataset, batch_size, collate_fn=None, shuffle=False,
                 seed=0, drop_last=True, num_local_io_workers=None,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.workers = int(num_local_io_workers or 0)
        # curriculum/efficiency sampler (e.g. DeepSpeedDataSampler): yields
        # index batches and carries checkpointable state — the engine
        # persists loader.data_sampler.state_dict() (reference
        # engine.py:3329 saves the sampler the same way)
        self.data_sampler = data_sampler
        self.epoch = 0
        n = len(dataset)
        self.len = (len(data_sampler) if data_sampler is not None
                    else n // batch_size if drop_last
                    else math.ceil(n / batch_size))

    def __len__(self):
        return self.len

    def set_epoch(self, epoch):
        self.epoch = epoch
        if hasattr(self.data_sampler, "set_epoch"):
            self.data_sampler.set_epoch(epoch)

    def _batch_indices(self):
        if self.data_sampler is not None:
            for idx in self.data_sampler:
                idx = np.asarray(idx)
                if idx.ndim == 0:
                    raise TypeError(
                        "data_sampler must yield BATCHES of indices "
                        "(lists/arrays), got a scalar — per-sample "
                        "samplers like DistributedSampler belong inside "
                        "a batch sampler, not here")
                yield idx
            return
        order = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        for b in range(self.len):
            yield order[b * self.batch_size:(b + 1) * self.batch_size]

    def _make(self, idx):
        return self.collate_fn([self.dataset[int(i)] for i in idx])

    def __iter__(self):
        if self.workers <= 0:
            for idx in self._batch_indices():
                yield self._make(idx)
            return
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(self.workers) as ex:
            futs = deque()
            it = self._batch_indices()
            for idx in it:
                futs.append(ex.submit(self._make, idx))
                if len(futs) > self.workers:
                    break
            while futs:
                batch = futs.popleft().result()
                nxt = next(it, None)
                if nxt is not None:
                    futs.append(ex.submit(self._make, nxt))
                yield batch


class PrefetchLoader:
    """Background-thread batch prefetch around ANY iterable loader (the
    decoupled producer role the reference gets from torch DataLoader worker
    processes): while the device runs step N, one filler thread assembles
    batches N+1..N+depth into a bounded queue.  Exceptions in the source
    iterator propagate to the consumer; each ``__iter__`` spins a fresh
    filler so epochs restart cleanly."""

    def __init__(self, loader, depth=2):
        self.loader = loader
        self.depth = max(1, int(depth))

    def __len__(self):
        return len(self.loader)

    def set_epoch(self, epoch):
        if hasattr(self.loader, "set_epoch"):
            self.loader.set_epoch(epoch)

    def __iter__(self):
        import queue
        import threading
        q = queue.Queue(maxsize=self.depth)
        END = object()
        stop = threading.Event()

        def put(item):
            # Bounded-queue put that gives up once the consumer is gone:
            # a plain q.put blocks forever if iteration is abandoned
            # (break / exception / GC), pinning `depth` batches per epoch.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def fill():
            try:
                for item in self.loader:
                    if not put(item):
                        return
                put(END)
            except BaseException as e:       # noqa: BLE001 — re-raised below
                put(e)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # Runs on StopIteration AND on GeneratorExit/break: release the
            # filler (it checks `stop` between bounded puts) and drain so it
            # is never parked on a full queue.
            stop.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)


class RepeatingLoader:
    """Reference ``runtime/dataloader.py`` RepeatingLoader: wrap an iterator to
    restart on StopIteration (pipeline engine uses this)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self.data_iter = iter(self.loader)
            return next(self.data_iter)
