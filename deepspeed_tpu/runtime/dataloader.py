"""Data loader — analog of reference ``runtime/dataloader.py``
(``DeepSpeedDataLoader``) + ``engine.py:1753 deepspeed_io``.

Single-controller difference: the reference gives each of the N processes a
``DistributedSampler`` shard of the dataset; here one process forms the
*global* batch (micro_batch × dp) and the engine device_puts it sharded over
the dp axis — the per-chip slice is what lands in each chip's HBM, so the
memory/behavior is the same, without the sampler rank bookkeeping.
"""

import math

import numpy as np

import jax


def _to_numpy(x):
    if isinstance(x, np.ndarray):
        return x
    try:
        import torch
        if isinstance(x, torch.Tensor):
            return x.detach().cpu().numpy()
    except ImportError:
        pass
    return np.asarray(x)


def default_collate(samples):
    """Stack a list of samples (each a tuple/list/dict/array) into batch arrays."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples])
                           for i in range(len(first)))
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    arrs = [_to_numpy(s) for s in samples]
    return np.stack(arrs)


class DeepSpeedDataLoader:
    """Iterates a map-style dataset in global batches.

    ``batch_size`` here is the *global* effective micro batch
    (micro_batch_per_gpu × dp_world_size), matching what the engine shards.
    """

    def __init__(self, dataset, batch_size, collate_fn=None, shuffle=False,
                 seed=0, drop_last=True, num_local_io_workers=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        n = len(dataset)
        self.len = n // batch_size if drop_last else math.ceil(n / batch_size)

    def __len__(self):
        return self.len

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(order)
        for b in range(self.len):
            idx = order[b * self.batch_size:(b + 1) * self.batch_size]
            samples = [self.dataset[int(i)] for i in idx]
            yield self.collate_fn(samples)


class RepeatingLoader:
    """Reference ``runtime/dataloader.py`` RepeatingLoader: wrap an iterator to
    restart on StopIteration (pipeline engine uses this)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            if hasattr(self.loader, "set_epoch"):
                self.loader.set_epoch(getattr(self.loader, "epoch", 0) + 1)
            self.data_iter = iter(self.loader)
            return next(self.data_iter)
