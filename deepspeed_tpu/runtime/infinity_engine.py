"""InfinityEngine — DeepSpeedEngine variant for ZeRO-Infinity parameter
streaming (``zero_optimization.offload_param.device: cpu|nvme``).

Reference: ZeRO-Infinity (``deepspeed/runtime/zero/stage3.py`` +
``partitioned_param_swapper.py:37`` + ``partitioned_param_coordinator.py:535``
prefetch + ``csrc/adam/cpu_adam_impl.cpp`` host optimizer).

TPU-native execution model (NOT the hook machinery): the model exposes an
``embed → blocks → head`` :class:`~.zero.infinity.StreamingSpec`; forward and
backward are *python-level* streams of per-block jitted calls —

  forward:   fetch(i+2) ─ overlap ─ x_{i+1} = block_jit(w_i, x_i); release(w_i)
  head:      loss, dx, d_resident = head_grad_jit(resident, x_L, batch)
  backward:  re-fetch(i) (reverse) ─ dw_i, dx = block_grad_jit(w_i, x_i, dx)
             (recompute-in-vjp: block activations never persist past the call)
             dw_i → host stash (async D2H)
  step:      host-native Adam/Adagrad/Lion sweep per block, updated bf16
             cache emitted in-kernel — params/optimizer state NEVER occupy
             HBM; the chip holds ≤ 3 blocks + boundary activations.

Single compiled executable per role (all blocks share one structure), so the
tunnel/XLA compile cost is O(1) in depth, and HBM param residency is O(block)
— the test suite asserts both.

Scope guards (loud, not silent): requires a model with ``streaming_parts``;
fp16 dynamic loss scaling, ZeRO++ quantization, and pipeline composition are
rejected; multi-host meshes are not yet routed (single-process meshes of any
device count work — batch stays dp-sharded, grads arrive GSPMD-reduced).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .engine import DeepSpeedEngine
from ..utils.logging import log_dist
from ..utils.timer import (BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER,
                           STEP_GLOBAL_TIMER)


class InfinityEngine(DeepSpeedEngine):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        cfg = self._config
        zc = cfg.zero_config
        if cfg.fp16_enabled:
            raise ValueError(
                "ZeRO-Infinity param streaming supports bf16/fp32 only — "
                "fp16 dynamic loss scaling would need a host-side unscale/"
                 "overflow pass; use bf16 (reference recommends the same)")
        if zc.zero_quantized_weights or zc.zero_quantized_gradients:
            raise ValueError("ZeRO++ quantization cannot compose with "
                             "param streaming (weights live on host)")
        # multi-process: every host holds the same store bytes (fetches
        # assemble via make_array_from_callback; grads arrive replicated or
        # are process-allgathered) and runs the identical host sweep —
        # exercised by the 2-process harness (tests/unit/multiproc)
        if not hasattr(self.module, "streaming_parts"):
            raise TypeError(
                "offload_param requires a model exposing streaming_parts() "
                "(see runtime/zero/infinity.StreamingSpec; models/llama.py "
                "implements it) — for monolithic models use "
                "offload_optimizer instead")
        self._spec = self.module.streaming_parts()
        # the base engine's optimizer-state NVMe swapper is superseded: the
        # BlockStore owns ALL state residency on this path
        self._nvme_swapper = None
        self._state_on_nvme = False

        opt_name = cfg.optimizer_name or "adam"
        oo = zc.offload_optimizer
        from .zero.infinity import BF16, BlockStore
        self._store = BlockStore(
            param_device=str(zc.offload_param.device),
            state_device=str(oo.device) if oo is not None and
            str(oo.device) != "none" else "cpu",
            nvme_path=(zc.offload_param.nvme_path or
                       (oo.nvme_path if oo is not None else None)),
            optimizer=opt_name, opt_params=dict(cfg.optimizer_params or {}),
            wire_dtype=(np.float32 if self.compute_dtype == jnp.float32
                        else BF16),
            grad_accum_fp32=self.gradient_accumulation_steps() > 1)
        self._resident_key = "__resident__"
        self._dev_blocks = {}      # key → device pytree (current working set)
        self._pending_fetch = {}   # key → _FetchHandle
        self._dev_resident = None
        self._acts = None          # saved block inputs for the current micro
        self._fwd_batch = None
        self._head_stash = None    # (d_resident, dx_L) from the fused head
        self.max_resident_blocks = 0   # high-water mark, asserted in tests
        self._build_jits()
        if self.params is not None:
            # base __init__ installed device params (small-model path) —
            # re-home them into the store and drop every device-side copy
            # (master/opt_state would otherwise pin HBM we promised to free)
            host = jax.tree_util.tree_map(
                lambda x: np.asarray(x, np.float32), self.params)
            self.params = None
            self.master = None
            self.opt_state = None
            self._install_host_tree(host)

    # ------------------------------------------------------------ plumbing
    def _build_jits(self):
        spec = self._spec

        def head_grad(res, x, *batch):
            def f(res, x):
                return spec.head_apply(res, x, *batch)
            loss, vjp = jax.vjp(f, res, x)
            dres, dx = vjp(jnp.ones_like(loss))
            return loss, dres, dx

        def block_grad(w, x, dy):
            _, vjp = jax.vjp(spec.block_apply, w, x)
            dw, dx = vjp(dy)
            return dw, dx

        def embed_grad(res, dx, *batch):
            def f(res):
                return spec.embed_apply(res, *batch)
            _, vjp = jax.vjp(f, res)
            return vjp(dx)[0]

        self._j_embed = jax.jit(spec.embed_apply)
        self._j_block = jax.jit(spec.block_apply)
        self._j_head = jax.jit(spec.head_apply)
        self._j_head_grad = jax.jit(head_grad)
        self._j_block_grad = jax.jit(block_grad)
        self._j_embed_grad = jax.jit(embed_grad)
        self._acc = jax.jit(
            lambda a, b: jax.tree_util.tree_map(jnp.add, a, b))

    @property
    def _rep_sharding(self):
        return NamedSharding(self.mesh, P())

    # --------------------------------------------------------------- install
    def _install_parameters(self, model_parameters):
        # base __init__ calls this before our __init__ body runs; defer —
        # the constructor re-homes self.params into the store afterwards
        if not hasattr(self, "_store"):
            return super()._install_parameters(model_parameters)
        host = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32), model_parameters)
        self._install_host_tree(host)

    def _install_host_tree(self, host):
        spec = self._spec
        for key in spec.block_keys:
            if key not in host:
                raise KeyError(f"streaming block key {key!r} missing from "
                               f"parameters (have {sorted(host)})")
            self._store.install_group(key, host[key])
        self._store.install_group(
            self._resident_key,
            {k: host[k] for k in spec.resident_keys})
        n = sum(self._store.param_bytes(k) for k in self._store.keys())
        log_dist(f"ZeRO-Infinity: {len(spec.block_keys)} blocks host-resident"
                 f" ({n / 2**30:.2f}G wire bytes; param_device="
                 f"{self._store.param_device} state_device="
                 f"{self._store.state_device})", ranks=[0])
        self.scale_state = self.loss_scaler.init()

    def initialize_parameters(self, rng_or_seed, *sample_inputs, **kw):
        """Block-by-block host init — the full parameter tree is never
        materialized anywhere (zero.Init at Infinity scale)."""
        if not self._flax:
            raise RuntimeError("initialize_parameters requires a flax Module")
        rng = (jax.random.PRNGKey(rng_or_seed)
               if isinstance(rng_or_seed, int) else rng_or_seed)
        spec = self._spec
        batch = tuple(np.asarray(x) for x in sample_inputs)
        # LOCAL cpu device — jax.devices() is the global list, and another
        # process's device is not addressable here
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            # no cpu backend registered (e.g. JAX_PLATFORMS=tpu): init lands
            # on the accelerator, materializing each block + the resident
            # group in HBM — loudly, since it breaks the host-init contract
            cpu = jax.local_devices()[0]
            log_dist(
                "ZeRO-Infinity: no cpu backend available for host-side "
                f"init — initializing blocks on {cpu.platform} instead "
                "(enable the cpu platform to keep init off-device)",
                ranks=[0])
        with jax.default_device(cpu):
            r_res, rng = jax.random.split(rng)
            res = spec.init_resident(r_res, *batch)
            x = jax.eval_shape(spec.embed_apply, res, *batch)
            x_host = jnp.zeros(x.shape, x.dtype)
            self._store.install_group(
                self._resident_key,
                jax.tree_util.tree_map(
                    lambda a: np.asarray(a, np.float32), res))
            del res
            for key in spec.block_keys:
                r_blk, rng = jax.random.split(rng)
                blk = spec.init_block(r_blk, key, x_host)
                self._store.install_group(key, jax.tree_util.tree_map(
                    lambda a: np.asarray(a, np.float32), blk))
                del blk
        self.scale_state = self.loss_scaler.init()
        log_dist(f"ZeRO-Infinity init: {len(spec.block_keys)} blocks "
                 f"(host, block-at-a-time)", ranks=[0])
        return None

    def _check_params(self):
        if not self._store.keys():
            raise RuntimeError(
                "engine has no parameters — pass model_parameters to "
                "initialize() or call engine.initialize_parameters(seed, "
                "*sample_inputs) first")

    # ----------------------------------------------------------- fetch logic
    def _fetch_async(self, key):
        if key in self._dev_blocks or key in self._pending_fetch:
            return
        self._pending_fetch[key] = self._store.start_fetch(key)

    def _get_block(self, key):
        if key not in self._dev_blocks:
            h = self._pending_fetch.pop(key, None) or \
                self._store.start_fetch(key)
            tree = self._store.finish_fetch(h, self._rep_sharding)
            self._dev_blocks[key] = tree
            self.max_resident_blocks = max(self.max_resident_blocks,
                                           len(self._dev_blocks))
        return self._dev_blocks[key]

    def _release_block(self, key):
        self._dev_blocks.pop(key, None)

    def _get_resident(self):
        if self._dev_resident is None:
            h = self._store.start_fetch(self._resident_key)
            self._dev_resident = self._store.finish_fetch(h,
                                                          self._rep_sharding)
        return self._dev_resident

    # ------------------------------------------------------------- execution
    def forward(self, *inputs, **kwargs):
        self._check_params()
        batch = self.shard_batch(*inputs)
        spec = self._spec
        keys = spec.block_keys
        if not self.training:
            res = self._get_resident()
            x = self._j_embed(res, *batch)
            for i, key in enumerate(keys):
                if i + 1 < len(keys):
                    self._fetch_async(keys[i + 1])
                w = self._get_block(key)
                x = self._j_block(w, x)
                self._release_block(key)
            return self._j_head(res, x, *batch)

        self.timers(FORWARD_GLOBAL_TIMER).start()
        res = self._get_resident()
        x = self._j_embed(res, *batch)
        acts = []
        for i, key in enumerate(keys):
            if i + 1 < len(keys):
                self._fetch_async(keys[i + 1])
            w = self._get_block(key)
            acts.append(x)
            x = self._j_block(w, x)
            self._release_block(key)
        # backward walks blocks in reverse: start its first fetch now so the
        # (NVMe) read overlaps the head computation
        self._fetch_async(keys[-1])
        # fused head: loss + dL/dx_L + d(resident) in one executable — the
        # head forward never runs twice
        loss, dres, dx = self._j_head_grad(res, x, *batch)
        self._head_stash = (dres, dx)
        self._acts = acts
        self._fwd_batch = batch
        self._micro_losses.append(loss)
        self._stashed_grads = ()   # sentinel: backward() has work to do
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        return loss

    def backward(self, loss=None, **kwargs):
        if self._head_stash is None:
            raise RuntimeError("backward() called without a prior forward() "
                               "in training mode")
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        spec = self._spec
        keys = spec.block_keys
        dres, dx = self._head_stash
        self._head_stash = None
        acts, batch = self._acts, self._fwd_batch
        self._acts = self._fwd_batch = None
        pending = None   # (key, dev grads) whose D2H is in flight
        for i in range(len(keys) - 1, -1, -1):
            if i - 1 >= 0:
                self._fetch_async(keys[i - 1])
            w = self._get_block(keys[i])
            dw, dx = self._j_block_grad(w, acts[i], dx)
            # kick the D2H copies now, but BLOCK on them one iteration
            # later — the host-side read of block i's grads overlaps the
            # device computing block i-1's (costs one extra in-flight grad
            # tree on the chip, still O(block))
            for leaf in jax.tree_util.tree_leaves(dw):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
            acts[i] = None
            self._release_block(keys[i])
            if pending is not None:
                self._store.accumulate_grads(*pending)
            pending = (keys[i], dw)
            del dw
        if pending is not None:
            self._store.accumulate_grads(*pending)
            pending = None
        res = self._get_resident()
        dres_embed = self._j_embed_grad(res, dx, *batch)
        self._store.accumulate_grads(self._resident_key,
                                     self._acc(dres, dres_embed))
        if not self.is_gradient_accumulation_boundary():
            # next micro's forward starts at block 0 — warm it (a boundary
            # step invalidates every fetch, so skip there)
            self._fetch_async(keys[0])
        self._stashed_grads = None
        self.timers(BACKWARD_GLOBAL_TIMER).stop()
        return loss

    def step(self):
        self._check_params()
        self.timers(STEP_GLOBAL_TIMER).start()
        if self.is_gradient_accumulation_boundary():
            # the streamed micro loss is UNscaled (no 1/gas factor baked into
            # head_apply), so the stash holds a SUM over the gas window:
            # average and clip here, folded into one grad multiplier
            gas = self.gradient_accumulation_steps()
            scale = 1.0
            clip = self._config.gradient_clipping
            if clip and clip > 0:
                gn = float(np.sqrt(self._store.grad_sq_norm())) / gas
                if gn > clip:
                    scale = clip / gn
            lr = self.get_lr()[0]
            self._store.optimizer_sweep(
                lr=lr, grad_scale=scale / gas if (gas > 1 or scale != 1.0)
                else None)
            # host caches changed → the device copies are stale
            self._dev_resident = None
            self._dev_blocks.clear()
            self._pending_fetch.clear()
            self.global_steps += 1
            self.global_samples += self.train_batch_size()
            if self.lr_scheduler is not None and \
                    hasattr(self.lr_scheduler, "step"):
                self.lr_scheduler.step()
                self._scheduler_reclaims_lr()
            for hook in self._post_step_hooks:
                hook(self)
            if self._micro_losses:
                self._last_loss = self._micro_losses
                self._micro_losses = []
            self._report_step_metrics(None)
        self.micro_steps += 1
        self.timers(STEP_GLOBAL_TIMER).stop()

    # ------------------------------------------------------------ state APIs
    def hbm_param_bytes(self):
        """Wire bytes of block params currently resident in device memory
        (the Infinity contract: O(working set), not O(model))."""
        return sum(self._store.param_bytes(k) for k in self._dev_blocks)

    def get_fp32_param(self, path=None):
        masters = self._store.export_master()
        out = dict(masters.pop(self._resident_key))
        out.update(masters)
        return out

    def _export_16bit_tree(self):
        # the inherited save_16bit_model path reads device params, which
        # never exist here — export the host master (base casts to the
        # compute dtype)
        return self.get_fp32_param()

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, exclude_frozen_parameters=False,
                        async_save=False):
        import os
        import pickle
        from .utils import ensure_directory_exists
        tag = tag or f"global_step{self.global_steps}"
        path = os.path.join(save_dir, str(tag), "infinity_state.pkl")
        ensure_directory_exists(path)
        # snapshot NOW (export_* deep-copies): the next optimizer_sweep may
        # mutate the host store while an async writer is mid-dump
        from .checkpoint_engine import collect_data_state
        state = {
            "master": self._store.export_master(),
            "opt": self._store.export_state(),
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler is not None and
                             hasattr(self.lr_scheduler, "state_dict")
                             else None),
            "client_state": client_state or {},
            **collect_data_state(self),
        }

        def write():
            with open(path, "wb") as f:
                pickle.dump(state, f)
            if save_latest:
                # deferred 'latest': only a fully-written checkpoint may
                # become the resume target (same contract as the async
                # orbax path in runtime/checkpoint_engine.py)
                with open(os.path.join(save_dir, "latest"), "w") as f:
                    f.write(str(tag))

        if async_save:
            import threading
            self.wait_for_checkpoint()
            self._ckpt_thread = threading.Thread(target=write, daemon=False)
            self._ckpt_thread.start()
        else:
            write()
        return path

    def wait_for_checkpoint(self):
        t = getattr(self, "_ckpt_thread", None)
        if t is not None:
            t.join()
            self._ckpt_thread = None

    def load_checkpoint(self, load_dir, tag=None, **kw):
        import os
        import pickle
        if tag is None:
            with open(os.path.join(load_dir, "latest")) as f:
                tag = f.read().strip()
        path = os.path.join(load_dir, str(tag), "infinity_state.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
        self._store.import_master(state["master"])
        self._store.import_state(state["opt"])
        self.global_steps = state["global_steps"]
        self.global_samples = state["global_samples"]
        self.micro_steps = state["micro_steps"]
        if state.get("lr_scheduler") is not None and \
                self.lr_scheduler is not None and \
                hasattr(self.lr_scheduler, "load_state_dict"):
            self.lr_scheduler.load_state_dict(state["lr_scheduler"])
        from .checkpoint_engine import restore_data_state
        restore_data_state(self, state)
        self._dev_resident = None
        self._dev_blocks.clear()
        self.scale_state = self.loss_scaler.init()
        return path, state.get("client_state", {})
