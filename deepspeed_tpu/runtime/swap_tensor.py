"""Tensor swapping to NVMe/disk — reference ``runtime/swap_tensor/``
(``AsyncTensorSwapper`` async_swapper.py, ``AsyncPartitionedParameterSwapper``
partitioned_param_swapper.py:37, optimizer swappers
partitioned_optimizer_swapper.py).

TPU-native shape: device arrays are fetched to host numpy (one DMA), then the
native aio thread pool streams them to per-tensor files; swap-in is the
mirror.  All transfers are async — callers hold a ``SwapHandle`` and
``wait()`` only at the point of use, so optimizer-state swap overlaps the
rest of ``step()`` the way the reference overlaps via aio events.
"""

import os
import shutil

import numpy as np

import jax

from ..utils.logging import logger


class SwapHandle:
    """One in-flight aio request + its host buffer."""

    def __init__(self, aio, req_id, buf, meta=None):
        self._aio = aio
        self._req = req_id
        self.buf = buf
        self.meta = meta or {}
        self._done = False

    def wait(self):
        if not self._done:
            self._aio.wait(self._req)
            self._done = True
        return self.buf

    @property
    def done(self):
        return self._done


class AsyncTensorSwapper:
    """Key→file tensor store with async read/write (reference
    ``runtime/swap_tensor/async_swapper.py``)."""

    def __init__(self, swap_dir, aio_handle=None, block_size=1 << 20,
                 queue_depth=32, thread_count=4):
        from ..ops.aio import AIOHandle
        self.swap_dir = os.path.abspath(swap_dir)
        os.makedirs(self.swap_dir, exist_ok=True)
        self.aio = aio_handle or AIOHandle(block_size=block_size,
                                           queue_depth=queue_depth,
                                           thread_count=thread_count)
        self._meta = {}   # key → (shape, dtype)
        self._inflight = []

    def _path(self, key):
        # injective encoding: '/' and '_' collide under plain replacement
        # ('a/b' vs 'a_b'), which would silently alias swap files
        safe = str(key).replace("_", "__").replace("/", "_s_")
        if os.sep != "/":
            safe = safe.replace(os.sep, "_s_")
        return os.path.join(self.swap_dir, f"{safe}.swp")

    # ---- write path
    def swap_out(self, key, array, async_op=True):
        """Device/host array → disk.  Returns a SwapHandle (already complete
        for async_op=False)."""
        host = np.ascontiguousarray(jax.device_get(array))
        self._meta[key] = (host.shape, host.dtype)
        if async_op:
            req = self.aio.async_write(host, self._path(key))
            h = SwapHandle(self.aio, req, host, {"key": key})
            self._inflight.append(h)
            return h
        self.aio.write(host, self._path(key))
        h = SwapHandle(self.aio, 0, host, {"key": key})
        h._done = True
        return h

    # ---- read path
    def swap_in(self, key, async_op=True):
        if key not in self._meta:
            raise KeyError(f"no swapped tensor under key {key!r}")
        shape, dtype = self._meta[key]
        buf = np.empty(shape, dtype)
        if async_op:
            req = self.aio.async_read(buf, self._path(key))
            h = SwapHandle(self.aio, req, buf, {"key": key})
            self._inflight.append(h)
            return h
        self.aio.read(buf, self._path(key))
        h = SwapHandle(self.aio, 0, buf, {"key": key})
        h._done = True
        return h

    def synchronize(self):
        """Wait for all in-flight requests (reference swap-wait epilogue)."""
        for h in self._inflight:
            h.wait()
        self._inflight = []

    def contains(self, key):
        return key in self._meta

    def release(self, key):
        self._meta.pop(key, None)
        try:
            os.remove(self._path(key))
        except OSError:
            pass

    def cleanup(self):
        self.synchronize()
        shutil.rmtree(self.swap_dir, ignore_errors=True)
        self._meta.clear()


class PartitionedOptimizerSwapper:
    """Optimizer-state residency manager for NVMe offload (reference
    ``runtime/swap_tensor/partitioned_optimizer_swapper.py:219``).

    Holds the optimizer-state pytree on disk between steps; ``swap_in_tree``
    brings it back as numpy (ready for the host CPUAdam kernels) and
    ``swap_out_tree`` streams it out again, both async.
    """

    def __init__(self, swap_dir, **aio_kwargs):
        self.swapper = AsyncTensorSwapper(swap_dir, **aio_kwargs)
        self._treedef = None

    def swap_out_tree(self, tree):
        leaves, self._treedef = jax.tree_util.tree_flatten(tree)
        handles = [self.swapper.swap_out(f"opt_{i}", leaf)
                   for i, leaf in enumerate(leaves)]
        return handles

    def swap_in_tree(self):
        return self.finish_swap_in(self.swap_in_tree_async())

    def swap_in_tree_async(self):
        """Kick off the disk reads; returns handles (callers start this at
        the grad-accum boundary so reads overlap backward compute —
        reference pipelined_optimizer_swapper overlap)."""
        if self._treedef is None:
            raise RuntimeError("nothing swapped out")
        # writes must land before reads of the same files
        self.swapper.synchronize()
        n = self._treedef.num_leaves
        return [self.swapper.swap_in(f"opt_{i}") for i in range(n)]

    def finish_swap_in(self, handles):
        leaves = [h.wait() for h in handles]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def synchronize(self):
        self.swapper.synchronize()

    def cleanup(self):
        self.swapper.cleanup()
