"""fp16 runtime (reference ``deepspeed/runtime/fp16/``): loss scaling lives in
``runtime/loss_scaler.py``; the flat-group FP16_Optimizer machinery is
subsumed by the engine's jitted apply step (``engine.py``); this package holds
the 1-bit communication-compressed optimizers."""
