"""Back-compat import path (reference ``deepspeed/runtime/fp16/loss_scaler
.py:270``) — the implementation lives in ``deepspeed_tpu/runtime/loss_scaler
.py`` (loss scaling is precision-neutral state on this engine, not an
fp16-only wrapper)."""

from ..loss_scaler import (DynamicLossScaler, StaticLossScaler,  # noqa: F401
                           create_loss_scaler, has_overflow)

# reference class name for the static variant
LossScaler = StaticLossScaler
