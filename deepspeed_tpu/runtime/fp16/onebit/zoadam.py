"""ZeroOneAdam — 0/1 Adam (reference ``runtime/fp16/onebit/zoadam.py:363``).

Compresses from step one (no dense warmup) and additionally *skips*
communication rounds: the sync interval doubles every ``local_step_scaler``
steps up to ``local_step_clipper`` (the reference's learning-rate-variance
policies), with pure-local momentum updates (and error feedback) in between.
The variance is refreshed from the synced momentum every
``var_update_scaler`` steps until ``var_freeze_step``.
"""

import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce
from .common import (build_local_grad_micro, build_onebit_apply,
                     check_compatible, init_state)


class ZeroOneAdam:

    name = "ZeroOneAdam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, var_freeze_step=100000,
                 var_update_scaler=16, local_step_scaler=32678,
                 local_step_clipper=16, cuda_aware=False,
                 comm_backend_name="mesh", lr_fn=None, **_):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.var_freeze_step = var_freeze_step
        self.var_update_scaler = var_update_scaler
        self.local_step_scaler = local_step_scaler
        self.local_step_clipper = local_step_clipper
        self.lr_fn = lr_fn

    def init(self, params, n):
        return init_state(params, n)

    def build_micro(self, engine):
        check_compatible(engine, self.name)
        return build_local_grad_micro(engine)

    def build_apply(self, engine):
        b1, b2 = self.betas
        eps, wd = self.eps, self.weight_decay
        var_freeze = self.var_freeze_step
        var_every = max(1, self.var_update_scaler)
        ls_scaler = max(1, self.local_step_scaler)
        ls_clip = self.local_step_clipper

        def leaf_update(g, p32, m, v, we, se, x, count, lr, axes, n):
            m_local = b1 * m + (1 - b1) * g
            # sync interval: 2^(count // local_step_scaler), clipped
            exp = jnp.minimum(count // ls_scaler, ls_clip)
            interval = jnp.left_shift(jnp.int32(1), exp)
            sync = (count % interval) == 0

            def do_sync(_):
                return compressed_allreduce(m_local, we, se, axes, n)

            def local(_):
                # local step: momentum advances locally; errors untouched
                return m_local, we, se

            m_, we_, se_ = jax.lax.cond(sync, do_sync, local, None)
            # (count-1) % every: step 1 always refreshes the variance — with
            # v=0 the update would be m/eps (unbounded) otherwise
            var_due = jnp.logical_and(count <= var_freeze,
                                      ((count - 1) % var_every) == 0)
            v_ = jnp.where(var_due, b2 * v + (1 - b2) * m_ * m_, v)
            # x = number of variance refreshes so far; bias-correct both
            # moments or the sparse v updates leave the denominator tiny for
            # the first ~1/(1-b2) refreshes (cold-start blow-up)
            vc = x + var_due.astype(jnp.float32)
            m_hat = m_ / (1.0 - b1**count.astype(jnp.float32))
            v_hat = v_ / (1.0 - b2**jnp.maximum(vc, 1.0))
            update = m_hat / (jnp.sqrt(v_hat) + eps)
            p_ = p32 - lr * (update + wd * p32)
            return p_, m_, v_, we_, se_, vc

        return build_onebit_apply(engine, leaf_update)
