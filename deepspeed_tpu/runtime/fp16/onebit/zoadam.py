"""ZeroOneAdam — 0/1 Adam (reference ``runtime/fp16/onebit/zoadam.py:363``).

Two phases, mirroring the reference:

* **Variance warmup** (``count <= var_freeze_step``): every step communicates.
  The worker-local momentum goes through the 1-bit error-feedback compressed
  allreduce, and the variance refreshes (every ``var_update_scaler``-th step)
  from the *synced* momentum — a deliberate deviation from the reference
  (which compresses the raw gradient and refreshes the variance from dense
  grads): tying ``v`` to the synced momentum keeps the per-element
  numerator/denominator scales matched under sign-compression noise, and
  keeps every state replica-identical with a single collective per step.

* **Local stepping** (``count > var_freeze_step``): the variance is frozen and
  communication rounds are skipped — the sync interval doubles every
  ``local_step_scaler`` steps up to ``2**local_step_clipper``.  Each worker
  advances params from its *local* momentum and records the applied deltas in
  a per-leaf accumulator ``acc`` (plus the summed lr in ``lrs``).  At a sync
  step it undoes its local drift (``p - acc``), compressed-allreduces the
  accumulated update (scaled to momentum units by the frozen denominator),
  re-applies the average, and recovers the synced momentum as ``-buf/lrs`` —
  the reference's ``momentum_accumulator`` reconcile (``zoadam.py:244-265``).
  After every sync step params and momentum are replica-identical again.
"""

import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce
from .common import (build_local_grad_micro, build_onebit_apply,
                     check_compatible, init_state)


class ZeroOneAdam:

    name = "ZeroOneAdam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, var_freeze_step=100000,
                 var_update_scaler=16, local_step_scaler=32678,
                 local_step_clipper=16, cuda_aware=False,
                 comm_backend_name="mesh", lr_fn=None, **_):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.var_freeze_step = var_freeze_step
        self.var_update_scaler = var_update_scaler
        self.local_step_scaler = local_step_scaler
        self.local_step_clipper = local_step_clipper
        self.lr_fn = lr_fn

    def init(self, params, n):
        return init_state(
            params, n,
            extra_fn=lambda p: {
                "vc": jnp.zeros((), jnp.float32),
                "acc": jnp.zeros(p.shape, jnp.float32),
                "lrs": jnp.zeros((), jnp.float32),
            })

    def build_micro(self, engine):
        check_compatible(engine, self.name)
        return build_local_grad_micro(engine)

    def build_apply(self, engine):
        b1, b2 = self.betas
        eps, wd = self.eps, self.weight_decay
        var_freeze = self.var_freeze_step
        var_every = max(1, self.var_update_scaler)
        ls_scaler = max(1, self.local_step_scaler)
        ls_clip = self.local_step_clipper

        def leaf_update(g, p32, m, v, we, se, x, count, lr, axes, n):
            vc, acc, lrs = x["vc"], x["acc"], x["lrs"]
            warm = count <= var_freeze
            # (count-1) % every: step 1 always refreshes the variance — with
            # v=0 the very first update would be m/eps (unbounded) otherwise.
            var_due = jnp.logical_and(warm, ((count - 1) % var_every) == 0)
            vc_ = vc + var_due.astype(jnp.float32)
            bc1 = 1.0 - b1 ** count.astype(jnp.float32)

            def denom(v_):
                v_hat = v_ / (1.0 - b2 ** jnp.maximum(vc_, 1.0))
                return jnp.sqrt(v_hat) + eps

            def warmup(args):
                # Every warmup step syncs: the worker-local momentum goes
                # through the 1-bit error-feedback allreduce, and the
                # variance refreshes (on its own schedule) from the *synced*
                # momentum — so moments and params stay replica-identical.
                m0, v0, we0, se0, acc0, lrs0, p0 = args
                m_, we_, se_ = compressed_allreduce(
                    b1 * m0 + (1 - b1) * g, we0, se0, axes, n)
                v_ = jnp.where(var_due, b2 * v0 + (1 - b2) * m_ * m_, v0)
                update = (m_ / bc1) / denom(v_) + wd * p0
                return (p0 - lr * update, m_, v_, we_, se_,
                        jnp.zeros_like(acc0), jnp.zeros_like(lrs0))

            def local_phase(args):
                m0, v0, we0, se0, acc0, lrs0, p0 = args
                m_loc = b1 * m0 + (1 - b1) * g  # worker-local momentum
                past = jnp.maximum(count - var_freeze, 0)
                expo = jnp.minimum(past // ls_scaler, ls_clip)
                interval = jnp.left_shift(jnp.int32(1), expo)
                sync = (count % interval) == 0

                update = (m_loc / bc1) / denom(v0) + wd * p0
                p_loc = p0 - lr * update
                acc_loc = acc0 - lr * update
                lrs_loc = lrs0 + lr

                def do_sync(_):
                    # Undo local drift, average the accumulated update,
                    # re-apply.  The wire tensor is expressed in *momentum
                    # units* (acc·denom·bc1/lrs ≈ the lr-weighted mean of the
                    # local momenta) so the error-feedback residuals keep one
                    # consistent scale across the warmup and local phases.
                    p_undo = p_loc - acc_loc
                    lrs_safe = jnp.maximum(lrs_loc, 1e-30)
                    # q folds the accumulated wd·p term into the recovered
                    # momentum — the reference does the same (its comm_buffer
                    # accumulates lr·(m/denom + wd·p) and exp_avg is rebuilt
                    # as -comm_buffer/lrs, zoadam.py:241-260).
                    q = -(acc_loc * denom(v0) / lrs_safe) * bc1  # v frozen
                    m_sync, we_, se_ = compressed_allreduce(
                        q, we0, se0, axes, n)
                    p_new = p_undo - (lrs_safe / bc1) * m_sync / denom(v0)
                    return (p_new, m_sync, jnp.zeros_like(acc_loc),
                            jnp.zeros_like(lrs_loc), we_, se_)

                def keep_local(_):
                    return p_loc, m_loc, acc_loc, lrs_loc, we0, se0

                p_, m_, acc_, lrs_, we_, se_ = jax.lax.cond(
                    sync, do_sync, keep_local, None)
                return p_, m_, v0, we_, se_, acc_, lrs_

            p_, m_, v_, we_, se_, acc_, lrs_ = jax.lax.cond(
                warm, warmup, local_phase, (m, v, we, se, acc, lrs, p32))
            x_ = {"vc": vc_, "acc": acc_, "lrs": lrs_}
            return p_, m_, v_, we_, se_, x_

        return build_onebit_apply(engine, leaf_update)
