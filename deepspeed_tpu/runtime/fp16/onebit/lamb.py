"""OnebitLamb (reference ``runtime/fp16/onebit/lamb.py:447``).

Warmup: exact LAMB (per-tensor trust ratio from ‖p‖/‖u‖).  Compression
phase: momentum goes through the 1-bit error-feedback allreduce and the
trust ratio is *frozen* at its last warmup value (the reference freezes
``scaling_coeff`` per layer at ``freeze_step`` because the post-compression
momentum magnitude is no longer comparable) — stored in the state's per-leaf
``extra`` scalar.
"""

import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce
from .common import (build_local_grad_micro, build_onebit_apply,
                     check_compatible, init_state)


class OnebitLamb:

    name = "OnebitLamb"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, freeze_step=100, max_coeff=10.0,
                 min_coeff=0.01, cuda_aware=False, comm_backend_name="mesh",
                 lr_fn=None, **_):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = freeze_step
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.lr_fn = lr_fn

    def init(self, params, n):
        # extra = frozen scaling coefficient, starts at 1
        return init_state(params, n,
                          extra_fn=lambda p: jnp.ones((), jnp.float32))

    def build_micro(self, engine):
        check_compatible(engine, self.name)
        return build_local_grad_micro(engine)

    def build_apply(self, engine):
        b1, b2 = self.betas
        eps, wd = self.eps, self.weight_decay
        freeze = self.freeze_step
        max_c, min_c = self.max_coeff, self.min_coeff

        def leaf_update(g, p32, m, v, we, se, coeff, count, lr, axes, n):
            def warmup(_):
                g_avg = jax.lax.pmean(g, axes)
                m_ = b1 * m + (1 - b1) * g_avg
                v_ = b2 * v + (1 - b2) * g_avg * g_avg
                u = m_ / (jnp.sqrt(v_) + eps) + wd * p32
                p_norm = jnp.sqrt(jnp.sum(p32 * p32))
                u_norm = jnp.sqrt(jnp.sum(u * u))
                ratio = jnp.where((p_norm > 0) & (u_norm > 0),
                                  jnp.clip(p_norm / u_norm, min_c, max_c),
                                  1.0)
                return m_, v_, we, se, u, ratio

            def compressed(_):
                m_local = b1 * m + (1 - b1) * g
                m_, we_, se_ = compressed_allreduce(m_local, we, se, axes, n)
                u = m_ / (jnp.sqrt(v) + eps) + wd * p32
                return m_, v, we_, se_, u, coeff  # frozen ratio

            m_, v_, we_, se_, u, ratio = jax.lax.cond(
                count <= freeze, warmup, compressed, None)
            p_ = p32 - lr * ratio * u
            return p_, m_, v_, we_, se_, ratio

        return build_onebit_apply(engine, leaf_update)
