"""Shared machinery for the 1-bit optimizers.

The 1-bit family needs *unreduced, per-worker* gradients (the whole point is
replacing the dense gradient/momentum allreduce), so these optimizers swap
both engine compiled functions:

  * micro-step: manual-SPMD (``shard_map``) value_and_grad whose output is
    the stack of per-worker local gradients ``[n_dp, *shape]`` (sharded over
    dp) — no reduction;
  * apply-step: one ``shard_map`` region doing warmup (exact pmean) or
    compressed (1-bit error-feedback momentum allreduce) updates per leaf.

Reference wiring: DeepSpeed disables ``enable_backward_allreduce`` when a
1-bit optimizer is configured and the optimizer's ``step`` drives the
compressed backend (``runtime/fp16/onebit/adam.py:14`` + engine).  Scope:
pure data-parallel meshes, ZeRO stage 0 (reference 1-bit optimizers are
likewise incompatible with ZeRO sharding).
"""

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ...comm.compressed import error_shapes


class OnebitState(NamedTuple):
    count: jnp.ndarray
    mu: object          # momentum, replicated
    nu: object          # variance, replicated
    worker_error: object  # per-leaf [n, we_size], sharded over dp
    server_error: object  # per-leaf [n, se_size], sharded over dp
    extra: object       # optimizer-specific per-leaf scalars (e.g. lamb coeff)


def _dp_axes(engine):
    from ....utils import groups
    mesh = engine.plan.mesh
    return tuple(a for a in groups.dp_axes() if mesh.shape.get(a, 1) > 1), mesh


def check_compatible(engine, name):
    if engine.zero_stage > 0:
        raise ValueError(f"{name} is incompatible with ZeRO stages > 0 "
                         "(reference 1-bit optimizers have the same scope)")
    if engine.mp_world_size > 1 or engine.seq_parallel_world_size > 1 or \
            engine.pp_world_size > 1:
        raise ValueError(f"{name} requires a pure data-parallel mesh")


def init_state(params, n, extra_fn=None):
    zeros_like_f32 = lambda p: jnp.zeros(p.shape, jnp.float32)
    mu = jax.tree_util.tree_map(zeros_like_f32, params)
    nu = jax.tree_util.tree_map(zeros_like_f32, params)

    def err_zeros(p, which):
        sizes = error_shapes(int(np.prod(p.shape, dtype=np.int64)), n)
        return jnp.zeros((n, sizes[which]), jnp.float32)

    we = jax.tree_util.tree_map(lambda p: err_zeros(p, 0), params)
    se = jax.tree_util.tree_map(lambda p: err_zeros(p, 1), params)
    extra = (jax.tree_util.tree_map(extra_fn, params)
             if extra_fn is not None else
             jax.tree_util.tree_map(lambda p: jnp.zeros((), jnp.float32),
                                    params))
    return OnebitState(count=jnp.zeros((), jnp.int32), mu=mu, nu=nu,
                       worker_error=we, server_error=se, extra=extra)


def build_local_grad_micro(engine):
    """Manual micro returning per-worker local grads stacked on axis 0."""
    plan = engine.plan
    axes, mesh = _dp_axes(engine)
    gas = engine.gradient_accumulation_steps()
    apply_fn = engine._effective_apply_fn()
    grad_dtype = engine.grad_accum_dtype

    from ...utils import make_scaled_loss_fn
    loss_fn = make_scaled_loss_fn(apply_fn, gas)

    def micro(params, scale, inputs):
        from ...utils import batch_input_specs
        batch_specs = batch_input_specs(inputs, axes,
                                        engine._n_replicated_batch_tail)
        param_specs = jax.tree_util.tree_map(lambda _: P(), params)

        def body(params, inputs):
            (_, loss), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, scale, inputs)
            loss = jax.lax.pmean(loss, axes)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(grad_dtype)[None], grads)
            return loss, grads

        grad_specs = jax.tree_util.tree_map(
            lambda p: P(*([axes] + [None] * p.ndim)), params)
        fn = shard_map(body, mesh=mesh, in_specs=(param_specs, batch_specs),
                       out_specs=(P(), grad_specs), check_vma=False)
        return fn(params, inputs)

    return micro


def build_onebit_apply(engine, leaf_update):
    """Shared apply-step: unscale, overflow check, per-leaf ``leaf_update``
    (the optimizer math, running inside shard_map with dp collectives
    available), overflow-skip select, loss-scale update.

    ``leaf_update(g, p32, m, v, we, se, extra, count, lr) ->
        (p32', m', v', we', se', extra')``
    """
    axes, mesh = _dp_axes(engine)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    scaler = engine.loss_scaler
    fp16 = engine._config.fp16_enabled
    compute_dtype = engine.compute_dtype
    opt = engine._onebit_opt
    lr_fn = getattr(opt, "lr_fn", None)

    def apply(params, master, opt_state, grad_acc, scale_state):
        has_master = master is not None
        target = master if has_master else params
        count = opt_state.count + 1
        lr = lr_fn(count) if lr_fn is not None else opt.lr

        p_specs = jax.tree_util.tree_map(lambda _: P(), target)
        g_specs = jax.tree_util.tree_map(
            lambda p: P(*([axes] + [None] * p.ndim)), target)
        e_specs = jax.tree_util.tree_map(lambda _: P(axes, None), target)
        x_specs = jax.tree_util.tree_map(lambda _: P(), opt_state.extra)

        def body(target, mu, nu, we, se, extra, grads, scale):
            inv = 1.0 / scale
            flat_t, treedef = jax.tree_util.tree_flatten(target)
            flat_m = treedef.flatten_up_to(mu)
            flat_v = treedef.flatten_up_to(nu)
            flat_we = treedef.flatten_up_to(we)
            flat_se = treedef.flatten_up_to(se)
            flat_x = treedef.flatten_up_to(extra)
            flat_g = treedef.flatten_up_to(grads)

            gs = [g[0].astype(jnp.float32) * inv for g in flat_g]
            if fp16:
                ofl = sum(jnp.sum(~jnp.isfinite(g)) for g in gs) > 0
                overflow = jax.lax.pmax(ofl.astype(jnp.float32), axes) > 0
            else:
                overflow = jnp.zeros((), jnp.bool_)

            outs = [
                leaf_update(g, p.astype(jnp.float32), m, v, w[0], s[0], x,
                            count, lr, axes, n)
                for g, p, m, v, w, s, x in zip(gs, flat_t, flat_m, flat_v,
                                               flat_we, flat_se, flat_x)
            ]

            def pick(new, old):
                return jnp.where(overflow, old, new)

            new_t = treedef.unflatten(
                [pick(o[0], p.astype(jnp.float32)).astype(p.dtype)
                 for o, p in zip(outs, flat_t)])
            new_m = treedef.unflatten(
                [pick(o[1], m) for o, m in zip(outs, flat_m)])
            new_v = treedef.unflatten(
                [pick(o[2], v) for o, v in zip(outs, flat_v)])
            new_we = treedef.unflatten(
                [pick(o[3], w[0])[None] for o, w in zip(outs, flat_we)])
            new_se = treedef.unflatten(
                [pick(o[4], s[0])[None] for o, s in zip(outs, flat_se)])
            new_x = treedef.unflatten(
                [jax.tree_util.tree_map(lambda n_, o_: pick(n_, o_), o[5], x)
                 for o, x in zip(outs, flat_x)])
            # post-reduction momentum norm (the exact grad norm would need a
            # dense allreduce, which 1-bit exists to avoid)
            gnorm = jnp.sqrt(
                sum(jnp.sum(jnp.square(m)) for m in
                    jax.tree_util.tree_leaves(new_m)))
            return new_t, new_m, new_v, new_we, new_se, new_x, overflow, gnorm

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, p_specs, p_specs, e_specs, e_specs, x_specs,
                      g_specs, P()),
            out_specs=(p_specs, p_specs, p_specs, e_specs, e_specs, x_specs,
                       P(), P()),
            check_vma=False)
        (new_target, new_m, new_v, new_we, new_se, new_x, overflow,
         gnorm) = fn(target, opt_state.mu, opt_state.nu,
                     opt_state.worker_error, opt_state.server_error,
                     opt_state.extra, grad_acc, scale_state.scale)

        new_opt = OnebitState(
            count=jnp.where(overflow, opt_state.count, count),
            mu=new_m, nu=new_v, worker_error=new_we, server_error=new_se,
            extra=new_x)
        if has_master:
            new_master = new_target
            new_params = jax.tree_util.tree_map(
                lambda m_: m_.astype(compute_dtype), new_master)
        else:
            new_master = None
            new_params = new_target
        new_scale = scaler.update(scale_state, overflow)
        return new_params, new_master, new_opt, new_scale, overflow, gnorm

    return apply
