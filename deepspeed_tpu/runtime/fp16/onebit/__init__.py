"""1-bit optimizers (reference ``runtime/fp16/onebit/``): OnebitAdam
(``adam.py:14``), OnebitLamb (``lamb.py:447``), ZeroOneAdam (``zoadam.py:363``)
— Adam/LAMB variants whose momentum is all-reduced with error-feedback 1-bit
sign compression (``runtime/comm/compressed.py``) after a full-precision
warmup phase."""

from .adam import OnebitAdam
from .lamb import OnebitLamb
from .zoadam import ZeroOneAdam
