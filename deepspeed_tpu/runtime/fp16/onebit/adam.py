"""OnebitAdam (reference ``runtime/fp16/onebit/adam.py:14``).

Phase 1 (``count < freeze_step``): exact Adam — gradients pmean'd in full
precision, both moments updated.  Phase 2: the variance is frozen and the
*momentum* is averaged with the 1-bit error-feedback compressed allreduce
(``runtime/comm/compressed.py``) — 32× less traffic than a dense allreduce.
No bias correction (matches the reference update
``p -= lr * exp_avg / (sqrt(exp_avg_sq) + eps)``).
"""

import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce
from .common import (build_local_grad_micro, build_onebit_apply,
                     check_compatible, init_state)


class OnebitAdam:

    name = "OnebitAdam"

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, freeze_step=100, cuda_aware=False,
                 comm_backend_name="mesh", lr_fn=None, **_):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = freeze_step
        self.lr_fn = lr_fn

    # engine hooks ---------------------------------------------------------
    def init(self, params, n):
        return init_state(params, n)

    def build_micro(self, engine):
        check_compatible(engine, self.name)
        return build_local_grad_micro(engine)

    def build_apply(self, engine):
        b1, b2 = self.betas
        eps, wd = self.eps, self.weight_decay
        freeze = self.freeze_step

        def leaf_update(g, p32, m, v, we, se, x, count, lr, axes, n):
            def warmup(_):
                g_avg = jax.lax.pmean(g, axes)
                m_ = b1 * m + (1 - b1) * g_avg
                v_ = b2 * v + (1 - b2) * g_avg * g_avg
                return m_, v_, we, se

            def compressed(_):
                m_local = b1 * m + (1 - b1) * g
                m_, we_, se_ = compressed_allreduce(m_local, we, se, axes, n)
                return m_, v, we_, se_

            m_, v_, we_, se_ = jax.lax.cond(count <= freeze, warmup,
                                            compressed, None)
            update = m_ / (jnp.sqrt(v_) + eps)
            p_ = p32 - lr * (update + wd * p32)
            return p_, m_, v_, we_, se_, x

        return build_onebit_apply(engine, leaf_update)
