"""DeepSpeedHybridEngine — RLHF train↔generate flip-flop (reference
``runtime/hybrid_engine.py:30``).

The reference wraps each layer in inference containers
(``create_inference_containers`` :274), gathers ZeRO-3 params layer-by-layer
during ``generate`` (``_zero3_forward`` :357) and fuses/unfuses LoRA
(:132-146).  TPU-native:

* the *same* jitted decode program (``inference/engine.py``) serves
  generation, fed the live training params — no module surgery, no weight
  copies; the jit cache is the "inference container";
* ZeRO-3 sharded params flow straight into the decode program — XLA's
  latency-hiding scheduler overlaps the per-layer all-gathers with compute,
  which IS the reference's layer-wise gather strategy, compiled;
* LoRA fuse = functional ``merge_lora`` on entry to generate (nothing to
  unfuse — training params are never mutated).

Selected by ``{"hybrid_engine": {"enabled": true}}`` (reference engine choice
``deepspeed/__init__.py:178-219``).
"""

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist
from .engine import DeepSpeedEngine


class DeepSpeedHybridEngine(DeepSpeedEngine):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._inference_engine = None
        self._lora_params = None
        self._lora_config = None
        self._lora_fused = False
        self._gen_count = 0
        he = self._config.hybrid_engine
        log_dist(f"HybridEngine ready: max_out_tokens={he.max_out_tokens}",
                 ranks=[0])

    # ----------------------------------------------------------------- lora
    def set_lora(self, lora_params, lora_config=None):
        """Register trainable LoRA adapters (path-keyed dict from
        ``deepspeed_tpu.linear.init_lora``); generate() merges them."""
        self._lora_params = lora_params
        self._lora_config = lora_config

    def fuse_lora_weight(self):
        """Parity API (reference :132): bake adapters into the params."""
        if self._lora_params is None or self._lora_fused:
            return
        from ..linear import merge_lora
        self.params = merge_lora(self.params, self._lora_params,
                                 self._lora_config)
        self._lora_fused = True

    def unfuse_lora_weight(self):
        if self._lora_params is None or not self._lora_fused:
            return
        from ..linear import unmerge_lora
        self.params = unmerge_lora(self.params, self._lora_params,
                                   self._lora_config)
        self._lora_fused = False

    # ------------------------------------------------------------- generate
    def _get_inference_engine(self):
        if self._inference_engine is None:
            from ..inference.config import DeepSpeedInferenceConfig
            from ..inference.engine import InferenceEngine
            he = self._config.hybrid_engine
            cfg = DeepSpeedInferenceConfig(
                max_out_tokens=he.max_out_tokens,
                dtype="bfloat16" if self._config.bfloat16_enabled else
                ("float16" if self._config.fp16_enabled else "float32"))
            self._inference_engine = InferenceEngine(
                (self.module, self.params), config=cfg)
        return self._inference_engine

    def _generation_params(self):
        self._check_params()   # restores host-offloaded params if needed
        params = self.params
        if self._lora_params is not None and not self._lora_fused:
            from ..linear import merge_lora
            params = merge_lora(params, self._lora_params, self._lora_config)
        return params

    def generate(self, input_ids, **kwargs):
        """KV-cached generation with the live training weights (reference
        ``generate`` :242 area: flip to inference containers, gather, run)."""
        eng = self._get_inference_engine()
        params = self._generation_params()
        # same pytree shapes/shardings step to step → decode jit cache replay
        eng.params = jax.tree_util.tree_map(
            lambda p, ref: p.astype(ref.dtype), params, eng.params)
        self._gen_count += 1
        out = eng.generate(input_ids, **kwargs)
        if self._config.hybrid_engine.release_inference_cache:
            eng.empty_cache()
        return out

    def eval(self):
        return super().eval()

    def train(self, mode=True):
        return super().train(mode)
