"""DeepSpeed-compatible JSON config system.

Analog of reference ``runtime/config.py:706`` (``DeepSpeedConfig``) with the
same JSON schema — the batch-size trinity, optimizer/scheduler sections,
fp16/bf16, zero_optimization, gradient clipping, monitoring, comms logging,
flops profiler, activation checkpointing, pipeline and mesh topology.

TPU-specific addition: a ``"mesh"`` section (``{"pp":1,"dp":-1,"sp":1,"tp":1,
"ep":1}``) declaring the device-grid factorization; absent, it is derived from
``pipeline``/``tensor_parallel``/``sequence_parallel_size`` keys the reference
spreads across subsystems.
"""

import json
import os
from typing import Any, Dict, Optional, Union

from pydantic import Field, model_validator

from .config_utils import DeepSpeedConfigModel, dict_raise_error_on_duplicate_keys
from .zero.config import DeepSpeedZeroConfig
from ..utils.logging import logger

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB_OPTIMIZER = "fusedlamb"
LION_OPTIMIZER = "lion"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
MUON_OPTIMIZER = "muon"
ADAGRAD_OPTIMIZER = "adagrad"

DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, LAMB_OPTIMIZER,
    FUSED_LAMB_OPTIMIZER, LION_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, SGD_OPTIMIZER,
    MUON_OPTIMIZER, ADAGRAD_OPTIMIZER,
]


class FP16Config(DeepSpeedConfigModel):
    """Reference fp16 section (``runtime/fp16/loss_scaler.py`` consumers)."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = Field(0.0, ge=0.0)  # 0 = dynamic
    initial_scale_power: int = Field(16, ge=0)
    loss_scale_window: int = Field(1000, ge=1)
    hysteresis: int = Field(2, ge=1)
    consecutive_hysteresis: bool = False
    min_loss_scale: float = Field(1.0, ge=0.0)
    fp16_master_weights_and_grads: bool = False


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    immediate_grad_update: bool = True


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: list = []
    # True → block_until_ready around each logged collective (precise
    # latency, but serializes the async pipeline — measurement changes the
    # program).  False (default) → dispatch-side timing only.
    sync_timing: bool = False


class CommsConfig(DeepSpeedConfigModel):
    comms_logger: CommsLoggerConfig = CommsLoggerConfig()

    @property
    def comms_logger_enabled(self):
        return self.comms_logger.enabled


class PrefetchConfig(DeepSpeedConfigModel):
    """``"comm_optimizations.overlap.prefetch"`` — the forward-direction
    ZeRO-3 param-gather prefetch (``runtime/zero/overlap.py``,
    docs/overlap.md).  Own gate, independent of ``overlap.enabled``: the
    two directions (backward grad reduce, forward param gather) compose
    but arm separately.  Reference configs arm it via an explicit
    ``zero_optimization.stage3_prefetch_bucket_size`` instead (0 there
    keeps it off); an explicit block here wins — loudly."""
    enabled: bool = False
    # bucket payload bound in MiB; 0 (default) = the 32 MiB overlap
    # default.  Configs armed via an explicit
    # zero_optimization.stage3_prefetch_bucket_size get bucket_mb stamped
    # from that ELEMENT count × the compute dtype itemsize.
    bucket_mb: float = Field(0.0, ge=0)
    # max buckets with their all-gather outstanding; further clamped per
    # model by stage3_max_live_parameters (overlap.live_window)
    max_inflight: int = Field(2, ge=1)


class OverlapConfig(DeepSpeedConfigModel):
    """``"comm_optimizations.overlap"`` — the bucketed backward-pass
    gradient-reduction scheduler (``runtime/zero/overlap.py``,
    docs/overlap.md).  Disabled (default) is bit-identical: the micro-step
    compiles to exactly the unbucketed program.  Enabled, the gradient
    reduce is split into ``bucket_mb``-bounded buckets dispatched inside
    the backward graph as each layer's gradients materialize, so XLA (or
    the manual qgZ pipeline) can hide the reduce under remaining backward
    compute.  The nested ``prefetch`` block is the forward mirror: the
    stage-3 param all-gather issued bucket by bucket under the forward
    compute."""
    enabled: bool = False
    # bucket size bound in MiB of gradient payload; fractional values are
    # allowed (tiny models need sub-MiB bounds to form >1 bucket)
    bucket_mb: float = Field(32.0, gt=0)
    # manual (qgZ) path only: how many buckets may have their inter-node
    # hop outstanding at once; the GSPMD path leaves scheduling to XLA
    max_inflight: int = Field(2, ge=1)
    # forward-direction stage-3 param-gather prefetch (own enable gate)
    prefetch: PrefetchConfig = PrefetchConfig()


class CommOptimizationsConfig(DeepSpeedConfigModel):
    """``"comm_optimizations"`` section — the topology-aware quantized
    collectives engine (``comm/collectives/``, docs/collectives.md).

    Disabled (default) is bit-identical to the flat collectives.  Enabled,
    the facade's eager collectives dispatch to hierarchical/quantized
    variants, and the ZeRO gradient/param paths switch to quantized wire
    traffic (qgZ/qwZ semantics) per the flags below.  The nested
    ``overlap`` block has its own ``enabled`` gate (the scheduler changes
    *when* reduces run, not what they carry, so it composes with either
    the flat or the quantized path)."""
    enabled: bool = False
    # intra-node reduce-scatter → inter-node op on 1/N → intra-node
    # all-gather; engages only when the group spans a topology hierarchy
    hierarchical_allreduce: bool = True
    # quantize param all-gather payloads (ZeRO++ qwZ analog)
    quantized_weights: bool = False
    # quantize gradient reduce-scatter payloads (ZeRO++ qgZ analog)
    quantized_gradients: bool = False
    # wire format: int8 | int4 | fp8 | fp6 | fp12
    wire_dtype: str = "int8"
    # per-message-size wire-format ladder: ascending [max_bytes, wire]
    # rungs ([null, wire] = catch-all, "fp32" = keep that band flat); sizes
    # above every rung use the global wire_dtype.  None (default) = global
    # wire_dtype everywhere, bit-identical to the pre-ladder engine.
    # Typically emitted by the autotuner (docs/autotuning.md) from measured
    # per-size probes — the EQuARX lesson that optimal quantization varies
    # by message size.
    wire_dtype_by_size: Optional[list] = None
    # elements per quantization scale group (lane-aligned down, min 128)
    quantization_group_size: int = Field(2048, ge=128)
    # devices per node for the hierarchy split; 0 = auto-detect from device
    # metadata (TPU slice / process boundaries) or DS_TPU_INTRA_NODE_SIZE
    intra_node_size: int = Field(0, ge=0)
    # messages under this many bytes always take the flat path
    min_message_size: int = Field(0, ge=0)
    # which micro-step architecture carries the quantized-gradient (qgZ)
    # training path (ISSUE 15, docs/zero.md "GSPMD-first ZeRO"):
    #   "gspmd" (default) — ONE jit over NamedSharding-annotated state with
    #     shard_map islands only around the codec+collective exchanges, so
    #     XLA's latency-hiding scheduler owns the program; compositions the
    #     islands cannot express yet (tp>1, hpZ/MiCS, MoE, dp×ep) keep the
    #     manual micro automatically;
    #   "flat_manual" — force the legacy full-manual shard_map micro
    #     (the ds_bench --zero-mode baseline lane).
    zero_mode: str = "gspmd"
    # bucketed backward-pass gradient-reduction scheduler (own enable gate)
    overlap: OverlapConfig = OverlapConfig()

    @model_validator(mode="after")
    def _check_zero_mode(self):
        from .zero.gspmd import ZERO_MODES
        if self.zero_mode not in ZERO_MODES:
            raise ValueError(
                f"comm_optimizations.zero_mode {self.zero_mode!r} unknown "
                f"(have {', '.join(ZERO_MODES)})")
        return self


class MoeConfig(DeepSpeedConfigModel):
    """``"moe"`` section — the expert-parallel MoE engine
    (``moe/engine.py``, docs/moe.md).

    ``enabled: false`` (default) and ``quantized_dispatch: false`` are both
    bit-identical to the plain GSPMD constraint dispatch (normalized-jaxpr
    contract, same as ``comm_optimizations``).  Enabled, the engine threads
    the noisy-gate rngs (per step, per layer) through flax apply and books
    routed-token accounting on the telemetry spine; ``quantized_dispatch``
    additionally routes the expert dispatch/return all-to-all through the
    manual-SPMD quantized exchange (blockwise codecs from
    ``comm/collectives/quantized.py``; hierarchical ICI/DCN variants picked
    by ``topology.factor_group``)."""
    enabled: bool = False
    # manual-SPMD quantized expert exchange (dispatch reduce + return
    # gather); False = the GSPMD constraint path, program-identical
    quantized_dispatch: bool = False
    # wire format of the quantized exchange: int8 | int4 | fp8 | fp6 |
    # fp12 | fp32 ("fp32" = the manual schedule with the raw fp payload).
    # A comm_optimizations.wire_dtype_by_size ladder, when present,
    # overrides this per payload size (the autotuner's per-size choice
    # applies to expert dispatch too).
    wire_dtype: str = "int8"
    # elements per quantization scale group (lane-aligned down, min 128)
    quantization_group_size: int = Field(2048, ge=128)
    # 2-hop dispatch (fp intra-node psum-scatter, quantized inter-node
    # all-to-all) when the ep axis spans a topology hierarchy
    hierarchical_dispatch: bool = True
    # devices per node for the ep-axis hierarchy split; 0 = auto-detect
    # (device metadata / DS_TPU_INTRA_NODE_SIZE), like the other collectives
    intra_node_size: int = Field(0, ge=0)
    # base seed for the per-step, per-layer noisy-gate rng fold-in
    # (RSample/Jitter policies); None = the config-level "seed"
    gating_seed: Optional[int] = None


class MonitorConfig(DeepSpeedConfigModel):
    """Reference ``monitor/config.py``: tensorboard/wandb/comet/csv."""

    class TensorBoardConfig(DeepSpeedConfigModel):
        enabled: bool = False
        output_path: str = ""
        job_name: str = "DeepSpeedJobName"

    class WandbConfig(DeepSpeedConfigModel):
        enabled: bool = False
        group: Optional[str] = None
        team: Optional[str] = None
        project: str = "deepspeed"

    class CSVConfig(DeepSpeedConfigModel):
        enabled: bool = False
        output_path: str = ""
        job_name: str = "DeepSpeedJobName"

    class CometConfig(DeepSpeedConfigModel):
        enabled: bool = False
        api_key: Optional[str] = None
        project: Optional[str] = None
        workspace: Optional[str] = None
        experiment_name: Optional[str] = None

    tensorboard: TensorBoardConfig = TensorBoardConfig()
    wandb: WandbConfig = WandbConfig()
    csv_monitor: CSVConfig = CSVConfig()
    comet: CometConfig = CometConfig()


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    recompute_fwd_factor: float = 0.0
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class PldConfig(DeepSpeedConfigModel):
    """``progressive_layer_drop`` section (reference
    ``runtime/progressive_layer_drop.py`` + PLD paper schedule)."""
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


class EigenvalueConfig(DeepSpeedConfigModel):
    """``eigenvalue`` section (reference ``runtime/eigenvalue.py`` — layer
    Hessian eigenvalues for compression's quantization-offset schedule)."""
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = ""
    layer_num: int = 0


class HybridEngineConfig(DeepSpeedConfigModel):
    """Reference ``deepspeed/runtime/config.py`` hybrid_engine section
    (RLHF train↔generate flip-flop, ``runtime/hybrid_engine.py:30``)."""
    enabled: bool = False
    max_out_tokens: int = 512
    inference_tp_size: int = 1
    release_inference_cache: bool = False
    pin_parameters: bool = True
    tp_gather_partition_size: int = 8


class DominoConfig(DeepSpeedConfigModel):
    """Domino µ-stream TP overlap (reference ``runtime/domino/transformer.py``
    — here ``runtime/domino/transformer.split_microstreams``): opt-in batch
    split into independent streams so the scheduler can hide TP collectives
    that GSPMD compilation leaves exposed.  A/B first (``domino_ab``) — on
    most TP meshes XLA already hides them and plain wins."""
    enabled: bool = False
    n_streams: int = 2


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """Reference ``runtime/activation_checkpointing/config.py`` schema; on TPU
    this steers ``jax.checkpoint`` policies (SURVEY.md §7)."""
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class PipelineConfig(DeepSpeedConfigModel):
    stages: Union[int, str] = "auto"
    partition: str = "best"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    # TPU addition: microbatch schedule executed inside one jitted program
    schedule: str = "1f1b"  # or "gpipe"


class MeshConfig(DeepSpeedConfigModel):
    """TPU device-grid factorization (dp=-1 → all remaining devices)."""
    pp: int = Field(1, ge=1)
    dp: int = -1
    sp: int = Field(1, ge=1)
    tp: int = Field(1, ge=1)
    ep: int = Field(1, ge=1)


class GradientClippingConfig(DeepSpeedConfigModel):
    enabled: bool = False


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: dict = {}


class DataTypesConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[str] = None


class AioConfig(DeepSpeedConfigModel):
    """Reference ``csrc/aio`` tuning knobs (``deepspeed/runtime/swap_tensor``)."""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True
    use_gds: bool = False


class CheckpointIntegrityConfig(DeepSpeedConfigModel):
    """Per-tag ``manifest.json`` (file list + sizes + checksums + config
    hash) committed after all tree writes; ``load_checkpoint`` verifies it
    and falls back to the newest *valid* tag on mismatch/partial tags."""
    enabled: bool = True
    keep_n: int = Field(0, ge=0)  # valid tags retained; 0 = unlimited
    save_retries: int = Field(3, ge=0)      # transient-FS retry attempts
    retry_backoff: float = Field(0.25, ge=0.0)  # seconds, doubles per retry


class FiniteGradsConfig(DeepSpeedConfigModel):
    """Opt-in NaN/Inf + grad-norm-spike step guard: a poisoned step is
    skipped via the fp16 loss-scaler skip path (also for bf16/fp32) and
    consecutive skips past ``max_consecutive_skips`` abort loudly.  Enabling
    it syncs the skip flag to host each boundary."""
    enabled: bool = False
    max_consecutive_skips: int = Field(5, ge=1)
    # skip when gnorm > factor × running mean of recent gnorms; 0 disables
    grad_norm_spike_factor: float = Field(0.0, ge=0.0)
    spike_warmup_steps: int = Field(10, ge=0)  # steps before spikes arm


class WatchdogConfig(DeepSpeedConfigModel):
    """Worker-side heartbeat files monitored by ``DSElasticAgent`` so a
    *hung* worker (stuck collective) is killed and relaunched, not just a
    dead one.  ``heartbeat_dir`` defaults to ``$DS_TPU_HEARTBEAT_DIR`` (the
    elastic agent exports a per-agent tempdir) and must be NODE-LOCAL per
    agent — see ``elasticity/watchdog.py``."""
    enabled: bool = False
    heartbeat_dir: str = ""
    stall_timeout: float = Field(300.0, gt=0.0)


class ResilienceConfig(DeepSpeedConfigModel):
    """``"resilience"`` JSON section — see docs/resilience.md."""
    checkpoint_integrity: CheckpointIntegrityConfig = \
        CheckpointIntegrityConfig()
    check_finite_grads: FiniteGradsConfig = FiniteGradsConfig()
    watchdog: WatchdogConfig = WatchdogConfig()


class TelemetryMetricsConfig(DeepSpeedConfigModel):
    """Live-metrics half of the telemetry block: registry + sinks."""
    enabled: bool = True
    # 0 = no HTTP endpoint (telemetry.prometheus_text() still renders)
    prometheus_port: int = Field(0, ge=0)
    # export/serve only on process 0 (the aggregation rank); False = every
    # rank exports its own series
    rank0_only: bool = True


class TelemetryConfig(DeepSpeedConfigModel):
    """``"telemetry"`` JSON section — see docs/observability.md.  Off by
    default = zero overhead: every emit site guards on the module-level
    ``deepspeed_tpu.telemetry.enabled`` flag, so the step path makes no
    telemetry allocations and losses are bit-identical to a build without
    the subsystem."""
    enabled: bool = False
    trace_dir: str = "telemetry"   # chrome trace + per-step JSONL land here
    trace_steps: int = Field(0, ge=0)  # stop step records after N; 0 = all
    # block on the accelerator at phase boundaries: CPU-accurate phase
    # attribution at the cost of serializing async dispatch
    fence: bool = False
    # wrap spans/steps in jax.profiler annotations so xplane captures
    # (engine.start_device_trace) carry the phase names
    device_profiler: bool = False
    metrics: TelemetryMetricsConfig = TelemetryMetricsConfig()


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: list = [2, 4, 6]
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch_size: bool = True


class DeepSpeedConfigError(Exception):
    pass


class DeepSpeedConfig:
    """Parsed + validated master config (reference ``runtime/config.py:706``)."""

    def __init__(self, config: Union[str, Dict, None], mpu=None, mesh_param=None):
        if config is None:
            config = {}
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(
                    f"DeepSpeed config path does not exist: {config}")
            with open(config) as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        elif isinstance(config, DeepSpeedConfig):
            self._param_dict = dict(config._param_dict)
        else:
            raise DeepSpeedConfigError(
                f"Expected a string path or dict, got {type(config)}")

        self.mesh_param = mesh_param
        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    # ------------------------------------------------------------------ parse
    def _initialize_params(self, pd):
        """Reference ``runtime/config.py:801 _initialize_params``."""
        self.train_batch_size = pd.get("train_batch_size", None)
        self.train_micro_batch_size_per_gpu = pd.get(
            "train_micro_batch_size_per_gpu", None)
        self.gradient_accumulation_steps = pd.get("gradient_accumulation_steps", None)
        self.steps_per_print = pd.get("steps_per_print", 10)
        # tokens per sample, for the telemetry step records' token-rate
        # metrics (docs/observability.md "MFU & HBM").  Unset, the engine
        # assumes axis 1 of the first input is the sequence — loudly.
        self.sequence_length = pd.get("sequence_length", None)
        if self.sequence_length is not None:
            if not isinstance(self.sequence_length, int) or \
                    self.sequence_length <= 0:
                raise DeepSpeedConfigError(
                    f"sequence_length must be a positive int, got "
                    f"{self.sequence_length!r}")
        self.dump_state = pd.get("dump_state", False)
        self.disable_allgather = pd.get("disable_allgather", False)
        self.communication_data_type = pd.get("communication_data_type", None)
        self.seq_parallel_communication_data_type = pd.get(
            "seq_parallel_comm_data_type", "fp32")
        self.prescale_gradients = pd.get("prescale_gradients", False)
        self.gradient_predivide_factor = pd.get("gradient_predivide_factor", 1.0)
        self.sparse_gradients_enabled = pd.get("sparse_gradients", False)
        if self.sparse_gradients_enabled:
            # reference runtime/sparse_tensor.py compresses torch sparse
            # embedding grads for the allreduce; XLA keeps embedding grads
            # dense (scatter-add fused into the backward) and there is no
            # sparse collective to route them through — reject rather than
            # silently ignore the knob
            raise ValueError(
                "sparse_gradients is a torch sparse-embedding optimization "
                "with no XLA analog (embedding grads are dense and the "
                "scatter-add fuses into the backward); remove the key")

        self.zero_config = DeepSpeedZeroConfig(**pd.get("zero_optimization", {}) or {})
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.fp16_config = FP16Config(**pd.get("fp16", {}) or {})
        self.bf16_config = BF16Config(**pd.get("bfloat16", pd.get("bf16", {})) or {})
        self.fp16_enabled = self.fp16_config.enabled
        self.bfloat16_enabled = self.bf16_config.enabled
        if self.fp16_enabled and self.bfloat16_enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        self.fp16_auto_cast = self.fp16_config.auto_cast
        self.loss_scale = self.fp16_config.loss_scale
        self.initial_dynamic_scale = 2**self.fp16_config.initial_scale_power
        self.dynamic_loss_scale_args = {
            "init_scale": 2**self.fp16_config.initial_scale_power,
            "scale_window": self.fp16_config.loss_scale_window,
            "min_scale": self.fp16_config.min_loss_scale,
            "delayed_shift": self.fp16_config.hysteresis,
        }

        grad_clip = pd.get("gradient_clipping", 0.0)
        self.gradient_clipping = float(grad_clip) if grad_clip else 0.0

        self.optimizer_name = None
        self.optimizer_params = None
        self.optimizer_legacy_fusion = False
        opt = pd.get("optimizer")
        if opt:
            self.optimizer_name = str(opt.get("type", "")).lower()
            self.optimizer_params = opt.get("params", {})
            self.optimizer_legacy_fusion = opt.get("legacy_fusion", False)

        self.scheduler_name = None
        self.scheduler_params = None
        sched = pd.get("scheduler")
        if sched:
            self.scheduler_name = sched.get("type")
            self.scheduler_params = sched.get("params", {})

        self.wall_clock_breakdown = pd.get("wall_clock_breakdown", False)
        self.memory_breakdown = pd.get("memory_breakdown", False)
        self.monitor_config = MonitorConfig(**{
            k: v
            for k, v in pd.items()
            if k in ("tensorboard", "wandb", "csv_monitor", "comet")
        })
        self.comms_config = CommsConfig(**pd.get("comms_logger", {})
                                        and {"comms_logger": pd.get("comms_logger")})
        self.comm_optimizations_config = CommOptimizationsConfig(
            **pd.get("comm_optimizations", {}) or {})
        from ..comm.collectives import WIRE_FORMATS, build_wire_ladder
        if self.comm_optimizations_config.wire_dtype not in WIRE_FORMATS:
            raise DeepSpeedConfigError(
                f"comm_optimizations.wire_dtype "
                f"{self.comm_optimizations_config.wire_dtype!r} unknown "
                f"(have {', '.join(WIRE_FORMATS)})")
        try:
            # normalize/validate the per-size ladder at config load, not at
            # first dispatch — a mistyped rung must fail bring-up loudly
            build_wire_ladder(
                self.comm_optimizations_config.wire_dtype_by_size)
        except ValueError as e:
            raise DeepSpeedConfigError(
                f"comm_optimizations.wire_dtype_by_size invalid: {e}") \
                from e
        # reference-compat: ``zero_optimization.overlap_comm: true`` (the
        # DeepSpeed knob for overlapping gradient reduction with backward)
        # arms the bucketed overlap scheduler unless the user pinned the
        # overlap block explicitly
        _ov_user = ((pd.get("comm_optimizations") or {}).get("overlap")
                    or {})
        if self.zero_config.overlap_comm and "enabled" not in _ov_user:
            self.comm_optimizations_config.overlap.enabled = True
        # reference-compat: an EXPLICIT ``stage3_prefetch_bucket_size``
        # arms the forward param-gather prefetch (the knob was previously
        # parsed but silently ignored); 0 keeps prefetch off (reference
        # semantics).  An explicit overlap.prefetch block wins — loudly,
        # so a config carrying both knows which knob is steering.
        _pf_user = (_ov_user.get("prefetch") or {}) \
            if isinstance(_ov_user, dict) else {}
        _zo_user = pd.get("zero_optimization") or {}
        _pf_knob = ("stage3_prefetch_bucket_size" in _zo_user
                    or "prefetch_bucket_size" in _zo_user)
        if _pf_knob and self.zero_config.stage >= 3:
            if "enabled" in _pf_user:
                logger.warning(
                    "zero_optimization.stage3_prefetch_bucket_size is "
                    "overridden by the explicit "
                    "comm_optimizations.overlap.prefetch block (prefetch "
                    "stays %s); the stage3 knob only arms the prefetch "
                    "when no explicit block is present",
                    "enabled" if self.comm_optimizations_config.overlap
                    .prefetch.enabled else "disabled")
            else:
                _pf = self.comm_optimizations_config.overlap.prefetch
                _pf.enabled = self.zero_config.prefetch_bucket_size > 0
                if _pf.enabled and "bucket_mb" not in _pf_user:
                    # the knob is an ELEMENT count (reference units) —
                    # stamp the byte bound here, where we know the knob
                    # was explicit (the field's 5e7 default must not
                    # silently size buckets)
                    _itemsize = 2 if (self.fp16_enabled
                                      or self.bfloat16_enabled) else 4
                    _pf.bucket_mb = (self.zero_config.prefetch_bucket_size
                                     * _itemsize / float(1 << 20))
        # "moe" block: the expert-parallel MoE engine (docs/moe.md).  Wire
        # format validated at config load like comm_optimizations — a
        # mistyped dispatch wire must fail bring-up, not first dispatch.
        self.moe_config = MoeConfig(**pd.get("moe", {}) or {})
        # "fp32" = manual schedule with the raw fp payload (the ladder's
        # flat rung).  Deliberately NOT imported from
        # moe.engine.DISPATCH_WIRES: importing the moe package here would
        # pull flax into every config parse; a sync test guards the
        # duplication instead
        _dispatch_wires = ("fp32", ) + tuple(WIRE_FORMATS)
        if self.moe_config.wire_dtype not in _dispatch_wires:
            raise DeepSpeedConfigError(
                f"moe.wire_dtype {self.moe_config.wire_dtype!r} unknown "
                f"(have {', '.join(_dispatch_wires)})")
        self.flops_profiler_config = FlopsProfilerConfig(
            **pd.get("flops_profiler", {}) or {})
        self.hybrid_engine = HybridEngineConfig(
            **pd.get("hybrid_engine", {}) or {})
        self.domino_config = DominoConfig(**pd.get("domino", {}) or {})
        self.activation_checkpointing_config = ActivationCheckpointingConfig(
            **pd.get("activation_checkpointing", {}) or {})
        self.pipeline_config = PipelineConfig(**pd.get("pipeline", {}) or {})
        self.pld_config = PldConfig(
            **pd.get("progressive_layer_drop", {}) or {})
        self.eigenvalue_config = EigenvalueConfig(
            **pd.get("eigenvalue", {}) or {})
        self.checkpoint_config = CheckpointConfig(**pd.get("checkpoint", {}) or {})
        self.data_types_config = DataTypesConfig(**pd.get("data_types", {}) or {})
        self.aio_config = AioConfig(**pd.get("aio", {}) or {})
        self.elasticity_config = ElasticityConfig(**pd.get("elasticity", {}) or {})
        self.resilience_config = ResilienceConfig(
            **pd.get("resilience", {}) or {})
        self.telemetry_config = TelemetryConfig(
            **pd.get("telemetry", {}) or {})
        # "autotuning" block: validated strictly here (unknown keys fail
        # bring-up loudly — autotuning/config.py forbids extras) so a
        # mistyped search knob never silently tunes the default space.
        # enabled: false (default) changes nothing; enabled: true is a
        # declaration consumed by ``autotuning.run_autotuning`` — the
        # engine itself never starts a search mid-initialize.
        from ..autotuning.config import AutotuningConfig
        try:
            self.autotuning_config = AutotuningConfig(
                **pd.get("autotuning", {}) or {})
        except Exception as e:
            raise DeepSpeedConfigError(f"autotuning config invalid: {e}") \
                from e
        if self.autotuning_config.enabled:
            logger.info(
                "autotuning.enabled: run the search via "
                "deepspeed_tpu.autotuning.run_autotuning(...) (or "
                "tools/autotune_smoke.py); initialize() itself does not "
                "start trials")

        self.gradient_accumulation_dtype = self.data_types_config.grad_accum_dtype

        # Mesh factorization (TPU addition): explicit "mesh" block wins, else
        # derive from reference-style keys.
        mesh_dict = dict(pd.get("mesh", {}) or {})
        if "tensor_parallel" in pd:
            mesh_dict.setdefault("tp", pd["tensor_parallel"].get("tp_size", 1))
        if "sequence_parallel_size" in pd:
            mesh_dict.setdefault("sp", pd["sequence_parallel_size"])
        if self.mesh_param is not None:
            # mesh_param: tuple (dp, sp) like reference initialize() :153-162
            mesh_dict.setdefault("dp", self.mesh_param[0])
            if len(self.mesh_param) > 1:
                mesh_dict.setdefault("sp", self.mesh_param[1])
        self.mesh_config = MeshConfig(**mesh_dict)

        self.load_universal_checkpoint = self.checkpoint_config.load_universal
        self.use_node_local_storage = self.checkpoint_config.use_node_local_storage

        self.seed = pd.get("seed", 1234)
        self.compile_config = pd.get("compile", {})
        self.graph_harvesting = pd.get("graph_harvesting", False)
        self.train_data_config = pd.get("data_efficiency", {})
        self.curriculum_enabled_legacy = bool(
            pd.get("curriculum_learning", {}).get("enabled", False))
        self.curriculum_params_legacy = pd.get("curriculum_learning", {})

    # ----------------------------------------------------- batch size trinity
    def _configure_train_batch_size(self):
        """Resolve train_batch = micro_batch * grad_accum * dp_world
        (reference ``runtime/config.py`` ``_set_batch_related_parameters``)."""
        self._dp_degree = None  # resolved lazily once mesh exists

        tb = self.train_batch_size
        mb = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        # Defer full resolution to resolve_batch_sizes(dp) — record raw here.
        self._raw_batch = (tb, mb, gas)

    def resolve_batch_sizes(self, dp_world_size):
        """Complete the trinity given the DP degree (called by the engine once
        the mesh is built).  Mirrors reference assertions (~config.py:837+).

        Under elastic training the agent exports the re-solved schedule as
        DS_ELASTIC_* env (reference: torchelastic rendezvous feeds the
        elastic batch math into ``_configure_train_batch_size``); those
        override the static JSON numbers so a rescaled restart picks up the
        new world's batch sizes without editing the config file."""
        import os as _os
        tb, mb, gas = self._raw_batch
        if (self.elasticity_config is not None
                and getattr(self.elasticity_config, "enabled", False)
                and "DS_ELASTIC_TRAIN_BATCH_SIZE" in _os.environ):
            tb = int(_os.environ["DS_ELASTIC_TRAIN_BATCH_SIZE"])
            mb = int(_os.environ.get("DS_ELASTIC_MICRO_BATCH_SIZE", mb or 1))
            gas = None  # derived from tb/(mb·dp) below
        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise DeepSpeedConfigError(
                    f"train_batch_size ({tb}) != micro_batch ({mb}) * "
                    f"grad_accum ({gas}) * dp_world ({dp_world_size})")
        elif tb is not None and mb is not None:
            gas = tb // (mb * dp_world_size)
            if gas == 0 or tb % (mb * dp_world_size) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size ({tb}) not divisible by micro_batch*dp "
                    f"({mb}*{dp_world_size})")
        elif tb is not None and gas is not None:
            if tb % (gas * dp_world_size) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size ({tb}) not divisible by gas*dp")
            mb = tb // (gas * dp_world_size)
        elif tb is not None:
            gas = 1
            if tb % dp_world_size != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size ({tb}) not divisible by dp ({dp_world_size})")
            mb = tb // dp_world_size
        elif mb is not None:
            gas = gas or 1
            tb = mb * gas * dp_world_size
        else:
            raise DeepSpeedConfigError(
                "At least train_batch_size or train_micro_batch_size_per_gpu "
                "must be set in the config")
        self.train_batch_size = tb
        self.train_micro_batch_size_per_gpu = mb
        self.gradient_accumulation_steps = gas
        self._dp_degree = dp_world_size
        return tb, mb, gas

    # ------------------------------------------------------------------ checks
    def _do_sanity_check(self):
        if self.optimizer_name is not None and self.fp16_enabled:
            pass  # fp16 + any optimizer is allowed; dynamic scale handles it
        if self.zero_optimization_stage > 0 and not (self.fp16_enabled
                                                     or self.bfloat16_enabled):
            logger.debug("ZeRO enabled with fp32 — allowed, but bf16 is the "
                         "TPU-recommended precision")

    def config_hash(self):
        """Stable content hash of the user config — recorded in each
        checkpoint manifest so a resume under a *different* config is
        flagged (warning, not error: elastic rescales legitimately resume
        with a re-solved batch schedule)."""
        import hashlib
        blob = json.dumps(self._param_dict, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def print_user_config(self):
        logger.info(json.dumps(self._param_dict, sort_keys=True, indent=4))
