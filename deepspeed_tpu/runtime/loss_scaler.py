"""Loss scaling — analog of reference ``runtime/fp16/loss_scaler.py:270``
(``LossScaler`` / ``DynamicLossScaler``), re-expressed as jit-friendly state.

The scaler state is a small pytree carried through the jitted train step; the
overflow check is ``isfinite`` over the gradient tree (reference
``_has_inf_or_nan`` stage3.py:2225), reduced with the grads' own collectives —
no separate serial scan.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray           # f32 scalar
    growth_tracker: jnp.ndarray  # i32: consecutive non-overflow steps
    hysteresis: jnp.ndarray      # i32: remaining tolerated overflows before shrink


def commit_scale_state(mesh, state):
    """Device-put a ``LossScaleState`` replicated onto ``mesh``.

    Freshly created / host-loaded jnp scalars carry UnspecifiedValue
    sharding, while the jitted step's outputs carry ``NamedSharding(P())``
    — jit treats that as a new signature and recompiles the ENTIRE micro
    step on the next call.  Every path that (re)creates the scale state
    (engine init, checkpoint load, universal load) must go through here."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.device_put(state, NamedSharding(mesh, P()))


class StaticLossScaler:
    """Reference ``LossScaler`` — fixed scale, never updates."""

    def __init__(self, scale=1.0):
        self.static_scale = float(scale)
        self.dynamic = False

    def init(self):
        return LossScaleState(scale=jnp.asarray(self.static_scale, jnp.float32),
                              growth_tracker=jnp.zeros((), jnp.int32),
                              hysteresis=jnp.ones((), jnp.int32))

    def update(self, state, overflow):
        return state

    def skip_on_overflow(self):
        # Static scaling still skips the step on overflow (reference fp16
        # optimizer semantics) but never adjusts scale.
        return True


class DynamicLossScaler(StaticLossScaler):
    """Reference ``DynamicLossScaler``: double every ``scale_window``
    overflow-free steps; on overflow consume hysteresis then halve."""

    def __init__(self, init_scale=2**16, scale_factor=2.0, scale_window=1000,
                 min_scale=1.0, delayed_shift=1, consecutive_hysteresis=False):
        super().__init__(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.delayed_shift = int(delayed_shift)
        self.consecutive_hysteresis = consecutive_hysteresis
        self.dynamic = True

    def init(self):
        return LossScaleState(scale=jnp.asarray(self.static_scale, jnp.float32),
                              growth_tracker=jnp.zeros((), jnp.int32),
                              hysteresis=jnp.asarray(self.delayed_shift, jnp.int32))

    def update(self, state, overflow):
        """Pure function → new state; called inside the jitted step."""

        def on_overflow(s):
            hysteresis = s.hysteresis - 1
            shrink = hysteresis <= 0
            new_scale = jnp.where(
                shrink, jnp.maximum(s.scale / self.scale_factor, self.min_scale),
                s.scale)
            new_hyst = jnp.where(shrink, jnp.asarray(self.delayed_shift, jnp.int32),
                                 hysteresis)
            return LossScaleState(scale=new_scale,
                                  growth_tracker=jnp.zeros((), jnp.int32),
                                  hysteresis=new_hyst)

        def on_ok(s):
            tracker = s.growth_tracker + 1
            grow = tracker >= self.scale_window
            new_scale = jnp.where(grow, s.scale * self.scale_factor, s.scale)
            new_tracker = jnp.where(grow, jnp.zeros((), jnp.int32), tracker)
            hyst = s.hysteresis if self.consecutive_hysteresis else \
                jnp.asarray(self.delayed_shift, jnp.int32)
            return LossScaleState(scale=new_scale, growth_tracker=new_tracker,
                                  hysteresis=hyst)

        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(overflow, a, b), on_overflow(state), on_ok(state))


def has_overflow(grads):
    """Any non-finite value in the grad tree (jit-friendly)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros((), jnp.bool_)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def create_loss_scaler(fp16_enabled, loss_scale=0.0, dynamic_args=None):
    """Factory mirroring reference ``CreateLossScaler`` (loss_scaler.py)."""
    if not fp16_enabled:
        return StaticLossScaler(1.0)
    if loss_scale and loss_scale > 0:
        return StaticLossScaler(loss_scale)
    args = dynamic_args or {}
    return DynamicLossScaler(
        init_scale=args.get("init_scale", 2**16),
        scale_window=args.get("scale_window", 1000),
        min_scale=args.get("min_scale", 1.0),
        delayed_shift=args.get("delayed_shift", 1),
    )
