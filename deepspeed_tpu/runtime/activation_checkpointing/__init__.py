from .checkpointing import (CheckpointPolicy, RNGStatesTracker, checkpoint,
                            configure, get_policy, get_rng_tracker,
                            is_configured, model_parallel_rng_seed,
                            non_reentrant_checkpoint, reset)
