"""Activation checkpointing — TPU rebuild of reference
``runtime/activation_checkpointing/checkpointing.py``.

The reference re-implements torch checkpointing (``CheckpointFunction`` :488,
``checkpoint()`` :948) with four extras: partitioning activations across TP
ranks (:377), CPU checkpointing, contiguous checkpoint buffers, and a CUDA RNG
state tracker (:124) so dropout inside the recomputed segment replays
identically.

On TPU every one of those maps onto ``jax.checkpoint`` (remat) policies:

* plain checkpointing       → ``jax.checkpoint(fn, policy=nothing_saveable)``
* selective ("contiguous
  memory" tradeoff)         → ``dots_saveable`` / ``dots_with_no_batch_dims``
  — keep the matmul outputs (the expensive recompute), rematerialize the
  cheap elementwise tail; this is the XLA-native analog of the reference's
  "checkpoint only what's costly to keep" knob.
* partition_activations     → saved residuals carry a sharding constraint on
  the ("sp","tp") axes so each rank stores 1/tp of every checkpoint
  (reference :377 slices the tensor; GSPMD does it by layout).
* cpu_checkpointing         → ``save_and_offload_only_these_names`` /
  offload-to-host policy: saved residuals live in pinned host memory.
* RNG replay                → free: jax PRNG keys are values, so recompute
  replays dropout bit-exactly with no state juggling.  The
  ``RNGStatesTracker`` below exists for Megatron-style model code that wants
  named per-TP-rank streams (reference ``CudaRNGStatesTracker`` :124).
"""

import contextlib
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ...utils.logging import logger

# jax.checkpoint policy registry (reference deepspeed_config_ activation
# checkpointing knobs → remat policies)
_POLICIES = {
    "none": None,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "checkpoint_dots": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


@dataclass
class CheckpointPolicy:
    """Resolved activation-checkpointing behavior (from the
    ``activation_checkpointing`` config block, reference
    ``runtime/activation_checkpointing/config.py``)."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    policy_name: str = "nothing_saveable"

    def jax_policy(self):
        if self.cpu_checkpointing:
            # offload saved residuals to pinned host memory (reference CPU
            # checkpointing :377 area) — offload everything remat would save
            try:
                return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                    "device", "pinned_host")
            except Exception:  # older jax: fall back to device-saved dots
                logger.warning("offload remat policy unavailable; "
                               "falling back to dots_saveable")
                return jax.checkpoint_policies.dots_saveable
        if self.contiguous_memory_optimization:
            # keep matmul outputs (the contiguous big buffers) — closest
            # XLA-native analog of the reference's contiguous buffer reuse
            return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        return _POLICIES.get(self.policy_name,
                             jax.checkpoint_policies.nothing_saveable)


_config: Optional[CheckpointPolicy] = None


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference ``checkpointing.configure()`` signature; accepts either a
    DeepSpeedConfig or explicit flags."""
    global _config
    cfg = CheckpointPolicy()
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing_config", None)
        if ac is not None:
            cfg.partition_activations = ac.partition_activations
            cfg.cpu_checkpointing = ac.cpu_checkpointing
            cfg.contiguous_memory_optimization = getattr(
                ac, "contiguous_memory_optimization", False)
            cfg.number_checkpoints = ac.number_checkpoints
    if partition_activations is not None:
        cfg.partition_activations = partition_activations
    if contiguous_checkpointing is not None:
        cfg.contiguous_memory_optimization = contiguous_checkpointing
    if num_checkpoints is not None:
        cfg.number_checkpoints = num_checkpoints
    if checkpoint_in_cpu is not None:
        cfg.cpu_checkpointing = checkpoint_in_cpu
    _config = cfg
    return cfg


def is_configured():
    return _config is not None


def reset():
    global _config
    _config = None


def get_policy():
    return _config or CheckpointPolicy()


def _partition_constraint(x):
    """Shard saved residuals over the model-parallel axes so each rank keeps
    1/tp of every activation (reference partition_activations :377)."""
    from ...utils import groups
    mesh = groups.get_global_mesh()
    if mesh is None or x.ndim == 0:
        return x
    from ..zero.partition import shard_spec
    spec = shard_spec(x.shape, mesh, ("tp", "sp"))
    try:
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, spec))
    except Exception:
        return x


def checkpoint(function, *args, policy=None, prevent_cse=True, **kwargs):
    """Megatron-compatible ``checkpoint(fn, *args)`` (reference :948):
    activations inside ``function`` are rematerialized on the backward pass.

    Unlike the reference this composes with jit/scan — it is a trace-time
    transform, not an autograd.Function."""
    cfg = get_policy()
    jp = (policy.jax_policy() if isinstance(policy, CheckpointPolicy)
          else policy if policy is not None else cfg.jax_policy())

    wrapped = function
    if cfg.partition_activations:
        inner = function

        def wrapped(*a, **kw):
            out = inner(*a, **kw)
            return jax.tree_util.tree_map(_partition_constraint, out)

    fn = jax.checkpoint(wrapped, policy=jp, prevent_cse=prevent_cse)
    return fn(*args, **kwargs)


def non_reentrant_checkpoint(function, *args, **kwargs):
    """Reference non-reentrant variant (:704) — identical under jax (there is
    no reentrant autograd engine); kept for API parity."""
    return checkpoint(function, *args, **kwargs)


def checkpoint_wrapper(function, policy=None):
    """Return a remat-wrapped callable (for scan-over-layers use)."""
    cfg = get_policy()
    jp = (policy.jax_policy() if isinstance(policy, CheckpointPolicy)
          else policy if policy is not None else cfg.jax_policy())
    return jax.checkpoint(function, policy=jp)


# --------------------------------------------------------------------- RNG
class RNGStatesTracker:
    """Named PRNG streams (reference ``CudaRNGStatesTracker`` :124).

    jax keys are values, so "states" here are keys; ``fork`` yields a
    sub-key derived per entry so model-parallel regions can draw
    rank-correlated or rank-independent randomness explicitly."""

    def __init__(self):
        self._keys = {}
        self._use_count = {}

    def reset(self):
        self._keys.clear()
        self._use_count.clear()

    def get_states(self):
        return dict(self._keys)

    def set_states(self, states):
        self._keys = dict(states)

    def add(self, name, seed):
        if name in self._keys:
            raise Exception(f"rng state {name} already exists")
        self._keys[name] = jax.random.key(seed)
        self._use_count[name] = 0

    @contextlib.contextmanager
    def fork(self, name="model-parallel-rng"):
        if name not in self._keys:
            raise Exception(f"rng state {name} not added")
        self._use_count[name] += 1
        yield jax.random.fold_in(self._keys[name], self._use_count[name])


_RNG_TRACKER = RNGStatesTracker()


def get_rng_tracker():
    return _RNG_TRACKER


def model_parallel_rng_seed(seed):
    """Reference ``model_parallel_cuda_manual_seed`` (:201): default stream
    shares ``seed`` across TP ranks; the model-parallel stream folds in the
    TP rank so dropout differs per shard."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("default", seed)
    # under SPMD all processes trace the same program; the model-parallel
    # stream is distinguished inside the traced fn via axis_index, so at the
    # host level we fold in only the process index
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718 + jax.process_index())
    return _RNG_TRACKER
