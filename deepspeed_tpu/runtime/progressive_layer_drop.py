"""Progressive Layer Drop — reference ``runtime/progressive_layer_drop.py``.

PLD (Zhang & He, "Accelerating Training of Transformer-Based Language
Models with Progressive Layer Dropping") anneals a keep probability
``theta(t)`` from 1 toward a floor ``theta_bar``; each transformer layer is
skipped (identity) with probability ``1 - theta(t)`` during training, which
cuts per-step compute while the schedule keeps early training stable.

The engine exposes the schedule exactly like the reference: when
``progressive_layer_drop.enabled`` is set, every training forward receives
``pld_theta`` (a traced scalar, so the jitted step does NOT recompile as
theta anneals), and ``update_state`` advances the schedule each global
step.  ``DeepSpeedTransformerLayer`` consumes ``pld_theta`` natively
(stochastic depth via the ``pld`` rng collection); custom flax models opt
in by accepting a ``pld_theta`` keyword.
"""

import numpy as np


class ProgressiveLayerDrop:
    """Keep-probability schedule: theta(t) = (1 - theta_bar)·e^(−gamma·t)
    inverted around the floor — starts at 1, decays to ``theta``."""

    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = float(theta)    # the floor (theta_bar)
        self.gamma = float(gamma)
        self.current_theta = 1.0

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        self.current_theta = ((1.0 - self.theta)
                              * float(np.exp(-self.gamma * global_step))
                              + self.theta)
        return self.current_theta

    def get_state(self):
        """Reference ``get_state``: the kwargs injected into the model."""
        return {"progressive_layer_drop": True,
                "pld_theta": self.get_theta()}
