"""Config model base — analog of reference ``runtime/config_utils.py:17``
(``DeepSpeedConfigModel``): pydantic model with

* ``"auto"``-value tolerance (reference ``config_utils.py:54-57``) for HF
  integration — any field may be the literal string "auto", resolved later;
* deprecated-field migration machinery (``deprecated`` / ``new_param`` kwargs);
* dict-style ``get``/``__getitem__`` helpers used across the engine.
"""

from functools import reduce
from typing import Any

from pydantic import BaseModel, ConfigDict, model_validator

from ..utils.logging import logger


class DeepSpeedConfigModel(BaseModel):
    """Base for all subsystem configs (same JSON schema as the reference so
    existing DeepSpeed configs run unmodified — SURVEY.md §5 config note)."""

    model_config = ConfigDict(
        validate_default=True,
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        extra="allow",
        arbitrary_types_allowed=True,
        protected_namespaces=(),
    )

    def __init__(self, strict=False, **data):
        if not strict:  # filter out "auto" values to use field defaults
            data = {
                k: v
                for k, v in data.items()
                if not (isinstance(v, str) and v == "auto"
                        and k not in self._fields_accepting_auto())
            }
        super().__init__(**data)

    @classmethod
    def _fields_accepting_auto(cls):
        out = set()
        for name, field in cls.model_fields.items():
            extra = getattr(field, "json_schema_extra", None) or {}
            if isinstance(extra, dict) and extra.get("accepts_auto"):
                out.add(name)
                if field.alias:
                    out.add(field.alias)
        return out

    @model_validator(mode="after")
    def _deprecated_fields_check(self):
        fields = type(self).model_fields
        for name, field in fields.items():
            extra = getattr(field, "json_schema_extra", None) or {}
            if isinstance(extra, dict) and extra.get("deprecated"):
                self._process_deprecated_field(name, field, extra)
        return self

    def _process_deprecated_field(self, dep_param, field, extra):
        fields_set = self.model_fields_set
        if dep_param not in fields_set:
            return
        new_param_fn = extra.get("new_param_fn", lambda x: x)
        param_value = new_param_fn(getattr(self, dep_param))
        new_param = extra.get("new_param", "")
        dep_msg = extra.get("deprecated_msg", "")
        logger.warning(f"Config parameter {dep_param} is deprecated" +
                       (f" use {new_param} instead" if new_param else "") +
                       (f". {dep_msg}" if dep_msg else ""))
        if new_param and extra.get("set_new_param", True):
            # Transfer to the new location unless the user set it explicitly.
            new_param_nested = new_param.split(".")
            if len(new_param_nested) > 1:
                nested_obj = reduce(getattr, new_param_nested[:-1], self)
                target = new_param_nested[-1]
            else:
                nested_obj = self
                target = new_param
            if target not in getattr(nested_obj, "model_fields_set", set()):
                setattr(nested_obj, target, param_value)

    # ------------------------------------------------------------ dict parity
    def get(self, key, default=None):
        return getattr(self, key, default)

    def __getitem__(self, key):
        return getattr(self, key)


def get_scalar_param(param_dict, param_name, param_default_value):
    """Reference ``runtime/config_utils.py`` helper."""
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys in the JSON config (reference behavior)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d
