"""TP overlap measurement (Domino parity artifact — see package docstring).

``measure_tp_overlap`` compiles a function and inspects the optimized HLO
schedule.  On GPU/CPU backends XLA's latency-hiding scheduler splits each
collective into ``<op>-start`` / ``<op>-done`` and moves independent
compute between them — exactly the overlap Domino hand-codes with
µ-streams.  The report counts

* ``collectives``      — collective ops in the optimized module,
* ``async_pairs``      — start/done-split (overlappable) collectives,
* ``overlapped_pairs`` — async collectives with ≥1 real compute op
                         scheduled inside the start→done window.

CAVEAT (measured 2026-07-31, v5e:2x2 AOT — tools/domino_overlap_tpu.py):
TPU optimized HLO does NOT express overlap as async pairs at all — each
collective stays one scheduled op whose ``collective_algorithm_config``
(ring emitters + scoped-memory barriers) pipelines the ICI transfer
in-op.  ``async_pairs == 0`` on TPU text therefore means "criterion
inapplicable", not "no overlap" — adjudicate with ``domino_ab``'s
wall-clock A/B on ≥2 chips (reference blog claims up to 1.3×; here the
compiler provides the schedule and this tool the evidence).
"""

import re

import jax

_COLLECTIVE_RE = re.compile(
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(-start|-done)?\b")
_COMPUTE_RE = re.compile(r"\b(fusion|dot|convolution|custom-call)\b")


def _schedule_lines(hlo_text):
    """Instruction lines of the entry computation in schedule order."""
    lines = []
    for raw in hlo_text.splitlines():
        line = raw.strip()
        if "=" in line and not line.startswith(("HloModule", "//", "#")):
            lines.append(line)
    return lines


def analyze_hlo_overlap(hlo_text):
    lines = _schedule_lines(hlo_text)
    collectives = 0
    async_pairs = 0
    overlapped = 0
    open_windows = {}  # op name → compute count since start
    for line in lines:
        m = _COLLECTIVE_RE.search(line)
        if m and m.group(2) == "-start":
            name = line.split("=", 1)[0].strip().lstrip("%")
            open_windows[name] = 0
            collectives += 1
            async_pairs += 1
            continue
        if m and m.group(2) == "-done":
            # operand name appears after the op
            for name in list(open_windows):
                if name in line:
                    if open_windows.pop(name) > 0:
                        overlapped += 1
                    break
            continue
        if m and m.group(2) is None:
            collectives += 1
        if _COMPUTE_RE.search(line):
            for name in open_windows:
                open_windows[name] += 1
    return {"collectives": collectives, "async_pairs": async_pairs,
            "overlapped_pairs": overlapped}


def measure_tp_overlap(fn, *args, **kwargs):
    """Compile ``fn`` (e.g. an engine micro-step closure) and report the
    collective-overlap structure of its optimized schedule."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    texts = compiled.as_text()
    if isinstance(texts, (list, tuple)):
        texts = "\n".join(texts)
    if not _COLLECTIVE_RE.search(texts or ""):
        # some backends (CPU) print thunks, not HLO — recompile with a dump
        # and read the post-optimization module
        import glob
        import tempfile
        tmp = tempfile.mkdtemp(prefix="ds_tpu_overlap_")
        lowered.compile(compiler_options={"xla_dump_to": tmp})
        parts = [open(p).read() for p in
                 sorted(glob.glob(f"{tmp}/*after_optimizations.txt"))]
        texts = "\n".join(parts) or texts
    report = analyze_hlo_overlap(texts)
    report["backend"] = jax.default_backend()
    report["overlapped"] = (report["async_pairs"] > 0
                            and report["overlapped_pairs"] > 0)
    return report


def DominoTransformerLayer(block_cls, *args, **kwargs):
    """Alias documenting the design decision (see package docstring): the
    standard block compiled under jit IS the overlap-scheduled form on TPU.
    Returns the block unchanged so reference-shaped code keeps working."""
    return block_cls(*args, **kwargs)
