"""Domino — TP communication/compute overlap (reference
``runtime/domino/transformer.py:518`` ``DominoTransformerLayer``).

The reference hides tensor-parallel all-reduces by hand: it splits each batch
into two µ-streams and interleaves one stream's collective with the other's
compute on separate CUDA streams.

The TPU equivalent is NOT a rewrite of the model: under ``jit``, XLA's
latency-hiding scheduler (LHS) already converts collectives into
``all-reduce-start``/``all-reduce-done`` pairs and schedules independent
compute between them — hand-interleaving inside a jitted program would just
be re-ordered by the compiler.  What the reference achieves with Domino's
µ-streams, the TPU build must *verify* instead: :func:`measure_tp_overlap`
lowers a step and reports whether the collectives in the optimized HLO are
asynchronous and have compute scheduled inside their windows.

``DominoTransformerLayer`` is therefore an explicit alias documenting the
design decision (the standard block IS the overlap-scheduled form), and the
measurement utility is the parity artifact.
"""

from .overlap import DominoTransformerLayer, measure_tp_overlap
from .transformer import (domino_ab, split_block_microstreams,
                          split_microstreams)
