"""Domino µ-stream TP blocks — the opt-in remedy when a TP collective is NOT
hidden by the scheduler.

Reference: ``deepspeed/runtime/domino/transformer.py:518`` — the reference
splits each batch into two µ-streams on separate CUDA streams and hand-
interleaves their TP all-reduces with the other stream's compute.

TPU-native form: CUDA streams don't exist; what XLA's latency-hiding
scheduler needs to overlap a collective is an *independent* computation to
schedule inside the start→done window.  ``split_microstreams`` creates that
independence explicitly — the batch is split into ``n_streams`` halves whose
subgraphs share only the (read-only) parameters, so stream B's matmuls are
legal filler for stream A's all-reduce window.  On a mesh where XLA already
hides the collectives (the common case, measured by
``measure_tp_overlap``), the plain form wins by avoiding the smaller-matmul
efficiency loss — run :func:`domino_ab` and keep the winner; that is the
A/B the reference's blog performs by hand.
"""

import time

import jax
import jax.numpy as jnp

from .overlap import measure_tp_overlap


def split_microstreams(apply_fn, n_streams=2, batch_argnum=0):
    """Wrap a loss-returning ``apply_fn(params, *inputs) -> scalar`` so every
    batch-like input splits into ``n_streams`` independent µ-streams.

    Returns the mean of the per-stream losses — identical to the unsplit
    loss for the uniform per-row-mean losses the engine's dp aggregation
    already assumes.  Gradients are exactly the unsplit gradients (the mean
    of per-half grads of per-half means).
    """
    if n_streams < 2:
        return apply_fn

    def split_apply(params, *inputs, **kw):
        B = inputs[batch_argnum].shape[0]
        if B % n_streams != 0:
            raise ValueError(
                f"domino n_streams={n_streams} must divide the micro batch "
                f"(got batch {B})")
        parts = [jnp.split(x, n_streams, axis=0)
                 if hasattr(x, "ndim") and x.ndim > 0 and x.shape[0] == B
                 else [x] * n_streams for x in inputs]
        losses = [apply_fn(params, *[p[i] for p in parts], **kw)
                  for i in range(n_streams)]
        return jnp.mean(jnp.stack(losses))

    return split_apply


def split_block_microstreams(block_fn, n_streams=2):
    """Activation-level variant: ``block_fn(params, x) -> y`` runs as
    ``n_streams`` independent half-batch calls (the reference's
    DominoTransformerLayer shape, for hand-built blocks)."""
    if n_streams < 2:
        return block_fn

    def split_block(params, x):
        outs = [block_fn(params, p)
                for p in jnp.split(x, n_streams, axis=0)]
        return jnp.concatenate(outs, axis=0)

    return split_block


def domino_ab(apply_fn, params, *inputs, n_streams=2, time_steps=0):
    """Compile the plain and µ-stream forms, report overlap structure for
    both, optionally wall-time them (``time_steps`` > 0 on real hardware),
    and name the winner.

    Decision rule: if the plain form's collectives are already all
    overlapped, plain wins (Domino's split only shrinks the matmuls); else
    the form with more overlapped pairs — wall time trumps structure when
    measured.
    """
    split_fn = split_microstreams(apply_fn, n_streams)
    report = {
        "plain": measure_tp_overlap(apply_fn, params, *inputs),
        "domino": measure_tp_overlap(split_fn, params, *inputs),
        "n_streams": n_streams,
    }

    def _time(fn):
        j = jax.jit(fn)
        out = j(params, *inputs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(time_steps):
            out = j(params, *inputs)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / time_steps

    if time_steps > 0:
        report["plain"]["step_s"] = _time(apply_fn)
        report["domino"]["step_s"] = _time(split_fn)
        report["winner"] = ("plain" if report["plain"]["step_s"]
                            <= report["domino"]["step_s"] else "domino")
    else:
        p, d = report["plain"], report["domino"]
        fully_hidden = (p["async_pairs"] > 0
                        and p["overlapped_pairs"] >= p["async_pairs"])
        report["winner"] = (
            "plain" if fully_hidden or
            d["overlapped_pairs"] <= p["overlapped_pairs"] else "domino")
    return report
