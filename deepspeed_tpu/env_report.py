"""``ds_report`` — environment / op-compatibility report.

Reference ``deepspeed/env_report.py`` prints a torch/cuda/nccl version matrix
and per-op_builder compatibility.  TPU version reports the JAX stack, device
inventory, and the native-op availability (Pallas kernels, C++ extensions).
"""

import importlib
import os
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
NO = f"{RED}[NO]{END}"


def _version(mod_name):
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return None


def op_report():
    """Native/kernel op availability (op_builder analog)."""
    rows = []
    from .ops.op_builder import ALL_OPS
    for name, builder in sorted(ALL_OPS.items()):
        try:
            compatible = builder().is_compatible()
        except Exception:
            compatible = False
        rows.append((name, compatible))
    return rows


def debug_report():
    import deepspeed_tpu
    rows = [
        ("deepspeed_tpu version", deepspeed_tpu.__version__),
        ("python version", sys.version.split()[0]),
        ("python platform", sys.platform),
    ]
    # aio engine probe (reference async_io report role).  Report-only: a
    # cold cache must NOT trigger the g++ JIT build mid-report (this tool
    # must never hang), and a setup probe is reported as such — the real
    # resolution happens at AIOHandle construction.
    try:
        from .ops.aio import AsyncIOBuilder, uring_available
        if not os.path.exists(AsyncIOBuilder().lib_path()):
            rows.append(("aio engine (auto)",
                         "not built yet (first AIOHandle builds it)"))
        elif uring_available():
            rows.append(("aio engine (auto)", "io_uring (setup probe ok)"))
        else:
            rows.append(("aio engine (auto)",
                         "thread-pool (io_uring setup refused)"))
    except Exception as e:
        rows.append(("aio engine (auto)", f"unavailable: {e}"))
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        v = _version(mod)
        rows.append((f"{mod} version", v if v else "not installed"))
    # Backend acquisition can BLOCK indefinitely (remote-TPU tunnels): a
    # report tool must never hang, so probe in a bounded worker thread.
    # DS_REPORT_DEVICE_TIMEOUT_S=0 skips the probe entirely.
    timeout_s = float(os.environ.get("DS_REPORT_DEVICE_TIMEOUT_S", "20"))
    probe = {}

    def _probe():
        try:
            import jax
            probe["backend"] = jax.default_backend()
            probe["count"] = jax.device_count()
            probe["devices"] = ", ".join(str(d) for d in jax.devices()[:8])
        except Exception as e:  # no backend available
            probe["error"] = str(e)

    if timeout_s > 0:
        import threading
        t = threading.Thread(target=_probe, daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            rows.append(("jax backend",
                         f"acquisition timed out after {timeout_s:.0f}s "
                         "(remote tunnel down?)"))
        elif "error" in probe:
            rows.append(("jax backend", f"unavailable ({probe['error']})"))
        else:
            rows.append(("jax backend", probe["backend"]))
            rows.append(("device count", probe["count"]))
            rows.append(("devices", probe["devices"]))
    else:
        rows.append(("jax backend", "probe skipped"))
    rows.append(("DS_ACCELERATOR", os.environ.get("DS_ACCELERATOR", "auto")))
    return rows


def main(hide_operator_status=False, hide_errors_and_warnings=False):
    if not hide_operator_status:
        print("-" * 70)
        print("DeepSpeed-TPU op compatibility")
        print("-" * 70)
        for name, ok in op_report():
            print(f"{name:.<40} {OKAY if ok else NO}")
    print("-" * 70)
    print("DeepSpeed-TPU general environment info:")
    print("-" * 70)
    for key, val in debug_report():
        print(f"{key:.<32} {val}")
    return 0


def cli_main():
    sys.exit(main())


if __name__ == "__main__":
    main()
