"""InferenceEngine (v1) — TPU-native re-design of reference
``inference/engine.py:41``.

Reference flow: build TP groups (:249), swap transformer blocks for fused
CUDA kernels or AutoTP-shard the linears (:403), optionally capture a CUDA
graph (:519), wrap ``generate`` (:608).

TPU flow:
* TP groups      → a ``tp`` axis on the global mesh (``utils/groups.py``);
* kernel-inject  → unnecessary as module surgery: XLA fuses the block; the
  hot kernels (attention) already route through ``ops/attention.py``
  (Pallas-ready).  ``replace_with_kernel_inject`` is accepted and simply
  keeps the same jitted path;
* AutoTP         → ``module_inject.auto_tp`` sharding rules + GSPMD;
* CUDA graph     → the jit cache: every (batch, seq) bucket compiles once
  and replays;
* generate       → static-shape KV cache (``models/cache.py``) with a jitted
  prefill and a ``lax.scan`` decode loop — the whole token loop is ONE
  XLA program, the TPU analog of FastGen's persistent decode kernels.
"""

import inspect
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..module_inject.auto_tp import AutoTP, shard_params_for_tp
from ..utils import groups
from ..utils.logging import log_dist, logger
from .config import DeepSpeedInferenceConfig


def _convert_injection_policy(policy):
    """Normalize the two injection_policy spellings to a rule table:

    * string-keyed: ``{"o_proj/kernel": PartitionSpec(...)}`` — native form,
      passed through;
    * reference form (``init_inference(..., injection_policy={Block:
      ('o_proj', )})``, reference ``replace_module.py``): class-keyed with a
      tuple of row-parallel (all-reduce-point) layer names — converted to
      row-parallel rules, with everything else left to AutoTP heuristics.
    """
    if not policy:
        return None
    rules = {}
    for key, val in policy.items():
        if isinstance(key, str):
            rules[key] = val
            continue
        # class-keyed reference form: val names the output/row layers
        names = val if isinstance(val, (tuple, list)) else (val, )
        for name in names:
            name = str(name).split(".")[-1]
            rules[f"{name}/kernel"] = P("tp", None)
    return rules or None


def _model_tp_rules(module):
    """Look up the ``tp_rules(config)`` helper next to the model class
    (our model families each export one — e.g. ``models/llama.py:tp_rules``)."""
    import sys
    mod = sys.modules.get(type(module).__module__)
    fn = getattr(mod, "tp_rules", None)
    if fn is not None and hasattr(module, "config"):
        try:
            return fn(module.config)
        except TypeError:
            pass
    return None


class InferenceEngine:
    """Wraps a flax module (+ params) for TP-sharded, KV-cached serving."""

    def __init__(self, model, config=None, params=None):
        if config is None:
            config = DeepSpeedInferenceConfig()
        elif isinstance(config, dict):
            config = DeepSpeedInferenceConfig(**config)
        self._config = config

        # accept (module, params) tuples and training engines
        if isinstance(model, tuple):
            model, params = model
        if hasattr(model, "module") and hasattr(model, "params"):  # engine
            params = model.params if params is None else params
            model = model.module
        self.module = model
        if params is None:
            raise ValueError(
                "InferenceEngine needs parameters: pass params=, a "
                "(module, params) tuple, or a training engine")

        tp_size = config.tensor_parallel.tp_size
        # mesh before init_distributed: the latter builds a default (all-dp)
        # mesh if none exists, which would pin tp=1
        if not groups.mesh_is_initialized():
            groups.initialize_mesh(tp=tp_size)
        if not dist.is_initialized():
            dist.init_distributed()
        self.mesh = groups.get_global_mesh()
        mesh_tp = self.mesh.shape.get("tp", 1)
        if tp_size > 1 and mesh_tp != tp_size:
            logger.warning(
                "init_inference requested tp_size=%d but the existing global "
                "mesh has tp=%d — serving with tp=%d (reset the mesh via "
                "groups.reset_mesh() before init_inference to change it)",
                tp_size, mesh_tp, mesh_tp)
        self._tp_enabled = mesh_tp > 1

        # precision: cast float leaves to the serving dtype (reference
        # engine.py:46 converts the module to config.dtype).  Accept every
        # spelling existing DeepSpeed configs use.
        _DTYPE_ALIASES = {
            "bf16": "bfloat16", "bfloat16": "bfloat16",
            "torch.bfloat16": "bfloat16",
            "fp16": "float16", "half": "float16", "float16": "float16",
            "torch.float16": "float16", "torch.half": "float16",
            "fp32": "float32", "float": "float32", "float32": "float32",
            "torch.float32": "float32", "torch.float": "float32",
            "int8": "int8", "torch.int8": "int8",
        }
        name = str(config.dtype).lower()
        dtype = jnp.dtype(_DTYPE_ALIASES.get(name, name))
        int8_requested = dtype == jnp.int8
        if int8_requested:
            # int8 dtype = the weight-quantization path (compute in bf16)
            dtype = jnp.dtype("bfloat16")
        self.dtype = dtype
        # weight-only quantized serving (reference inference/quantization):
        # 2-D+ float weights are stored as int8/int4 wire format + scales
        # (HBM at ~1 byte/weight); each jitted impl dequantizes at entry,
        # so XLA materializes fp weights transiently per step while the
        # resident copy stays quantized.
        self._quant_bits = None
        self._quant_meta = {}
        if config.quant.enabled or int8_requested:
            self._quant_bits = int(getattr(config.quant.weight, "num_bits",
                                           8) or 8)
        if config.replace_with_kernel_inject or config.use_triton:
            log_dist("kernel injection/use_triton: XLA fusion + the "
                     "Pallas-backed attention core already cover this path",
                     ranks=[0])

        def cast(x):
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(dtype)
            return x
        params = jax.tree.map(cast, params)

        # TP sharding (AutoTP analog); injection_policy overrides
        rules = None
        if self._tp_enabled:
            policy = _convert_injection_policy(config.injection_policy)
            rules = (policy or _model_tp_rules(model)
                     or AutoTP.derive_rules(params))
            log_dist(f"AutoTP: {len(rules)} sharding rules", ranks=[0])
        if self._quant_bits is not None and self._tp_enabled:
            if int8_requested and not config.quant.enabled:
                # dtype=int8 alias + TP previously served bf16 unquantized
                # with a warning — keep that compat behavior; only an
                # EXPLICIT quant config hard-errors
                logger.warning(
                    "dtype=int8 with tensor parallelism: weight-only "
                    "quant does not compose with TP yet — serving "
                    "unquantized bf16")
                self._quant_bits = None
            else:
                raise NotImplementedError(
                    "weight-only quantized serving does not compose with "
                    "tensor parallelism yet (quant grouping is laid out "
                    "pre-shard); drop tensor_parallel or quant")
        ckpt = config.checkpoint or config.checkpoint_config.checkpoint_dir
        if ckpt is not None and not isinstance(ckpt, (str, os.PathLike)):
            raise NotImplementedError(
                "checkpoint= takes a directory path here (training-engine "
                "layout or a save_mp_checkpoint_path snapshot); the "
                "reference's dict/JSON load-policy descriptors are not "
                "supported")
        if self._quant_bits is not None and not ckpt:
            # when a checkpoint will overwrite the weights, skip quantizing
            # the constructor params — load_checkpoint (re)quantizes what it
            # restores
            params = self._quantize_weights(params,
                                            config.quant.weight.group_size)
        with self.mesh:
            if rules is not None:
                self.params = shard_params_for_tp(params, self.mesh, rules)
            else:
                self.params = self._replicate(params)
        self._tp_rules = rules

        self._accepts_positions = "positions" in inspect.signature(
            type(model).__call__).parameters
        self._accepts_decode = "decode" in inspect.signature(
            type(model).__call__).parameters

        self._jit_forward = jax.jit(self._forward_impl)
        self._jit_prefill = jax.jit(self._prefill_impl)
        self._jit_decode = jax.jit(self._decode_impl,
                                   static_argnames=("steps", "do_sample",
                                                    "top_k", "top_p",
                                                    "eos_token_id"))
        self._cache_struct = {}

        # reference init_inference checkpoint flow: `checkpoint=` loads
        # weights at construction (training-engine layout OR an inference
        # snapshot written by save_mp_checkpoint_path), and
        # `save_mp_checkpoint_path=` snapshots the served tree (post-cast,
        # post-quant) for fast reload of large models.
        if ckpt:
            self.load_checkpoint(str(ckpt))
        save_path = (config.save_mp_checkpoint_path
                     or config.checkpoint_config.save_mp_checkpoint_path)
        if save_path:
            self.save_serving_checkpoint(str(save_path))

    def _replicate(self, tree):
        """device_put every leaf replicated on the serving mesh."""
        return jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x),
                                     NamedSharding(self.mesh, P())), tree)

    # ---------------------------------------------------- weight-only quant
    def _quantize_weights(self, params, group_size):
        """Shared wire-format quantization (``inference/quant_serving``)."""
        from .quant_serving import quantize_tree
        out, meta = quantize_tree(params, self._quant_bits, group_size)
        self._quant_meta.update(meta)
        return out

    def _dequantize(self, params):
        """Inverse of :meth:`_quantize_weights`, traced inside each jitted
        impl — the resident params stay quantized, fp copies exist only
        transiently inside the step."""
        if self._quant_bits is None:
            return params
        from .quant_serving import dequantize_tree
        return dequantize_tree(params, self._quant_meta, self.dtype)

    # ------------------------------------------------------------- forward
    def _forward_impl(self, params, input_ids):
        return self.module.apply({"params": self._dequantize(params)},
                                 input_ids)

    def forward(self, input_ids, **kwargs):
        """Full (non-cached) forward → logits.  Reference engine forward
        w/ graph replay (``inference/engine.py:538``) ≙ the jit cache."""
        if "attention_mask" in kwargs:
            mask = kwargs.pop("attention_mask")
            if mask is not None and not bool(jnp.all(jnp.asarray(mask) == 1)):
                raise NotImplementedError(
                    "forward() does not apply padding masks; strip padding "
                    "or use the ragged (inference v2) engine")
        for k in kwargs:
            logger.warning("forward(): ignoring unsupported argument %r", k)
        with self.mesh:
            return self._jit_forward(self.params, jnp.asarray(input_ids))

    __call__ = forward

    # -------------------------------------------------------------- cache
    def _init_cache(self, batch, max_len):
        key = (batch, max_len)
        if key not in self._cache_struct:
            shapes = jax.eval_shape(
                lambda: self.module.init(
                    jax.random.PRNGKey(0),
                    jnp.zeros((batch, max_len), jnp.int32), decode=True))
            self._cache_struct[key] = shapes["cache"]
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                            self._cache_struct[key])

    def _prefill_impl(self, params, cache, input_ids):
        params = self._dequantize(params)
        kw = {"positions": jnp.arange(input_ids.shape[1])[None, :]
              } if self._accepts_positions else {}
        logits, mut = self.module.apply({"params": params, "cache": cache},
                                        input_ids, decode=True,
                                        mutable=["cache"], **kw)
        return logits[:, -1, :], mut["cache"]

    def _decode_impl(self, params, cache, first_logits, rng, pos0, *, steps,
                     do_sample, top_k, eos_token_id, temperature, top_p):
        """ONE compiled XLA program for the whole decode loop."""
        params = self._dequantize(params)

        def sample(logits, key):
            if not do_sample:
                return jnp.argmax(logits, axis=-1)
            logits = logits / jnp.maximum(temperature, 1e-6)
            if top_k > 0:
                kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if top_p < 1.0:
                sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(sorted_logits, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
                cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
                logits = jnp.where(logits < cutoff, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1)

        eos = -1 if eos_token_id is None else eos_token_id

        def body(carry, _):
            cache, logits, rng, pos, done = carry
            rng, sub = jax.random.split(rng)
            tok = sample(logits, sub)
            tok = jnp.where(done, eos if eos >= 0 else 0, tok)
            done = done | (tok == eos)
            kw = ({"positions": pos[None, None] + jnp.zeros(
                (tok.shape[0], 1), jnp.int32)}
                  if self._accepts_positions else {})
            out, mut = self.module.apply(
                {"params": params, "cache": cache}, tok[:, None], decode=True,
                mutable=["cache"], **kw)
            return (mut["cache"], out[:, -1, :], rng, pos + 1, done), tok

        B = first_logits.shape[0]
        init = (cache, first_logits, rng, pos0,
                jnp.zeros((B, ), dtype=bool))
        (_, _, _, _, _), toks = lax.scan(body, init, None, length=steps)
        return toks.T  # [B, steps]

    # ------------------------------------------------------------ generate
    def generate(self, input_ids, max_new_tokens=None, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
                 rng=None, **kwargs):
        """Token-id generation (reference ``engine.py:608`` wraps HF
        ``generate``; here the loop is native and fully jitted)."""
        if not self._accepts_decode:
            raise ValueError(f"{type(self.module).__name__} has no KV-cache "
                             "decode path")
        if "attention_mask" in kwargs:
            mask = kwargs.pop("attention_mask")
            if mask is not None and not bool(jnp.all(jnp.asarray(mask) == 1)):
                raise NotImplementedError(
                    "generate() assumes unpadded same-length prompts; "
                    "left-padded attention_mask batching is the ragged "
                    "(inference v2) engine's job")
        for k in kwargs:
            logger.warning("generate(): ignoring unsupported argument %r", k)
        input_ids = jnp.asarray(input_ids)
        if input_ids.ndim == 1:
            input_ids = input_ids[None, :]
        B, S0 = input_ids.shape
        if max_new_tokens is None:
            steps = max(self._config.max_out_tokens - S0, 1)
        else:
            steps = int(max_new_tokens)
            if steps <= 0:
                return input_ids
        max_pos = getattr(getattr(self.module, "config", None),
                          "max_position_embeddings", None)
        if max_pos is not None:
            if S0 >= max_pos:
                raise ValueError(f"prompt length {S0} ≥ model "
                                 f"max_position_embeddings {max_pos}")
            if S0 + steps > max_pos:
                logger.warning(
                    "generate: clamping %d new tokens to %d "
                    "(max_position_embeddings=%d)", steps, max_pos - S0,
                    max_pos)
                steps = max_pos - S0
        max_len = S0 + steps
        rng = jax.random.PRNGKey(0) if rng is None else rng

        with self.mesh:
            cache = self._init_cache(B, max_len)
            logits, cache = self._jit_prefill(self.params, cache, input_ids)
            new = self._jit_decode(
                self.params, cache, logits, rng, jnp.int32(S0), steps=steps,
                do_sample=do_sample, top_k=top_k, eos_token_id=eos_token_id,
                temperature=temperature, top_p=top_p)
        return jnp.concatenate([input_ids, new], axis=1)

    # --------------------------------------------------------- checkpoints
    def save_serving_checkpoint(self, save_dir):
        """Snapshot the SERVED params tree (post-cast/quant/shard) for fast
        reload — the reference's ``save_mp_checkpoint_path`` role.  Layout:
        ``{dir}/params/`` (orbax) + ``serving_meta.json`` (quant meta)."""
        import json
        import os
        from ..runtime.checkpoint_engine import _pytree_save
        os.makedirs(save_dir, exist_ok=True)
        _pytree_save(os.path.join(save_dir, "params"), self.params)
        meta = {"quant_bits": self._quant_bits,
                "dtype": str(self.dtype),
                "quant_meta": {k: [list(m[0]), str(np.dtype(m[1])), int(m[2])]
                               for k, m in self._quant_meta.items()}}
        with open(os.path.join(save_dir, "serving_meta.json"), "w") as f:
            json.dump(meta, f)
        log_dist(f"serving checkpoint saved to {save_dir}", ranks=[0])
        return save_dir

    def _load_serving_checkpoint(self, load_dir):
        import json
        import os
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..runtime.checkpoint_engine import _pytree_restore
        with open(os.path.join(load_dir, "serving_meta.json")) as f:
            meta = json.load(f)
        if meta.get("dtype") and meta["dtype"] != str(self.dtype):
            raise ValueError(
                f"serving checkpoint dtype={meta['dtype']} does not match "
                f"this engine's serving dtype {self.dtype}; build the "
                "engine with the matching dtype")
        if (meta.get("quant_bits") or None) != self._quant_bits:
            raise ValueError(
                f"serving checkpoint quant_bits={meta.get('quant_bits')} "
                f"does not match this engine ({self._quant_bits}); build "
                "the engine with the matching quant config")
        restored = _pytree_restore(os.path.join(load_dir, "params"))
        with self.mesh:
            if self._tp_rules is not None:
                # TP engine: re-apply the sharding rules to the restored
                # tree (the snapshot stores global arrays)
                self.params = shard_params_for_tp(restored, self.mesh,
                                                  self._tp_rules)
            else:
                self.params = self._replicate(restored)
        self._quant_meta = {
            k: (tuple(s), np.dtype(d), int(g))
            for k, (s, d, g) in meta.get("quant_meta", {}).items()}
        log_dist(f"serving checkpoint loaded from {load_dir}", ranks=[0])
        return self

    def load_checkpoint(self, load_dir, tag=None):
        """Load weights: a serving snapshot (``save_serving_checkpoint``)
        or the ``model/`` tree of a training-engine checkpoint (layout:
        ``runtime/checkpoint_engine.py``)."""
        import os
        from ..runtime.checkpoint_engine import _pytree_restore
        load_dir = os.path.abspath(load_dir)
        if os.path.exists(os.path.join(load_dir, "serving_meta.json")):
            return self._load_serving_checkpoint(load_dir)
        if tag is None:
            with open(os.path.join(load_dir, "latest")) as f:
                tag = f.read().strip()
        restored = _pytree_restore(os.path.join(load_dir, str(tag), "model"))
        if self._quant_bits is not None:
            # quantized engine: re-quantize the restored float weights (the
            # resident tree holds wire-format dicts, not arrays)
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._quant_meta.clear()

            def cast(x):
                x = jnp.asarray(x)
                return (x.astype(self.dtype)
                        if jnp.issubdtype(x.dtype, jnp.floating) else x)

            restored = jax.tree.map(cast, restored)
            quantized = self._quantize_weights(
                restored, self._config.quant.weight.group_size)
            with self.mesh:
                self.params = self._replicate(quantized)
            return self
        # preserve dtype AND the TP sharding applied in __init__
        self.params = jax.tree.map(
            lambda new, old: jax.device_put(
                jnp.asarray(new).astype(old.dtype), old.sharding), restored,
            self.params)
        return self

    @property
    def config(self):
        return self._config

    def empty_cache(self):
        self._cache_struct.clear()
