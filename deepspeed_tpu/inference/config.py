"""Inference config — same JSON schema as reference ``inference/config.py``
(``DeepSpeedInferenceConfig``, ``DeepSpeedTPConfig`` :333) so existing
DeepSpeed inference configs run unmodified.  CUDA-only knobs
(``use_triton``, cuda-graph) are accepted and mapped to their XLA analogs
(jit compilation cache *is* the graph capture) or ignored with a log line.
"""

from typing import Any, Dict, Optional

from pydantic import Field

from ..runtime.config_utils import DeepSpeedConfigModel

#: the blockwise quantizer's minimum group — one TPU lane row.  Canonical
#: home is here (dependency-light) so config defaults and the quantizer
#: (``quant_serving``) agree by construction.
LANE_GROUP = 128


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """Reference ``inference/config.py`` TP block."""
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class DeepSpeedMoEConfig(DeepSpeedConfigModel):
    enabled: bool = True
    ep_size: int = 1
    moe_experts: list = Field(default_factory=lambda: [1], alias="num_experts")
    ep_mp_group: Optional[Any] = None
    ep_group: Optional[Any] = None


class QuantTypeConfig(DeepSpeedConfigModel):
    enabled: bool = False
    num_bits: int = 8
    # default derives from the TPU lane width: anything smaller just trips
    # the quantizer's clamp-and-warn path on every quantized-serving run
    group_size: int = LANE_GROUP
    group_dim: int = 0
    symmetric: bool = True


class InferenceQuantConfig(DeepSpeedConfigModel):
    enabled: bool = False
    activation: QuantTypeConfig = Field(default_factory=QuantTypeConfig)
    weight: QuantTypeConfig = Field(default_factory=QuantTypeConfig)
    qkv: QuantTypeConfig = Field(default_factory=QuantTypeConfig)


class InferenceCheckpointConfig(DeepSpeedConfigModel):
    checkpoint_dir: Optional[str] = None
    save_mp_checkpoint_path: Optional[str] = None
    base_dir: Optional[str] = None


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    """Top-level inference engine config (reference ``inference/config.py``)."""

    replace_with_kernel_inject: bool = Field(False, alias="kernel_inject")
    dtype: str = "bfloat16"
    tensor_parallel: DeepSpeedTPConfig = Field(
        default_factory=DeepSpeedTPConfig, alias="tp")
    enable_cuda_graph: bool = False  # XLA: jit cache plays this role
    use_triton: bool = False
    triton_autotune: bool = False
    zero: Dict = Field(default_factory=dict)
    triangular_masking: bool = Field(True, alias="tm")
    moe: DeepSpeedMoEConfig = Field(default_factory=DeepSpeedMoEConfig)
    quant: InferenceQuantConfig = Field(default_factory=InferenceQuantConfig)
    checkpoint: Optional[Any] = None
    base_dir: str = ""
    set_empty_params: bool = False
    save_mp_checkpoint_path: Optional[str] = None
    checkpoint_config: InferenceCheckpointConfig = Field(
        default_factory=InferenceCheckpointConfig, alias="ckpt_config")
    return_tuple: bool = True
    training_mp_size: int = 1
    replace_method: str = "auto"
    injection_policy: Optional[Dict] = Field(None, alias="injection_dict")
    injection_policy_tuple: Optional[tuple] = None
    config: Optional[Dict] = None
    max_out_tokens: int = Field(1024, alias="max_tokens")
    min_out_tokens: int = Field(1, alias="min_tokens")
    transposed_mode: bool = False
    mp_size: int = Field(1, deprecated=True)

    def __init__(self, **data):
        # legacy alias: mp_size → tensor_parallel.tp_size
        # (reference inference/config.py handles the same migration)
        mp = data.pop("mp_size", None)
        super().__init__(**data)
        if mp is not None and int(mp) > 1 and self.tensor_parallel.tp_size == 1:
            self.tensor_parallel.tp_size = int(mp)
