"""Ragged state management (reference ``inference/v2/ragged/``):
``BlockedAllocator`` (block free-list, ``blocked_allocator.py``),
``BlockedKVCache`` (paged KV storage, ``kv_cache.py``),
``DSSequenceDescriptor`` + ``DSStateManager`` (``ragged_manager.py:19``).

TPU shape discipline: the cache is ONE array per model —
``[L, 2, num_blocks, block_size, Hkv, Dh]`` — and every sequence owns a row
of a fixed-width block table ``[max_seqs, max_blocks_per_seq]``; the jitted
ragged forward only ever sees static shapes (the "ragged" part is metadata).
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp


class KVCacheExhausted(RuntimeError):
    """The block pool cannot satisfy an allocation (capacity, not a bug).

    Carries ``wanted_blocks`` / ``free_blocks`` so a serving scheduler can
    catch-and-preempt (``serving/scheduler.py``) while genuine programming
    errors keep surfacing as other exception types.  Subclasses
    ``RuntimeError`` so pre-existing ``except RuntimeError`` callers keep
    working."""

    def __init__(self, wanted_blocks, free_blocks, detail=""):
        self.wanted_blocks = int(wanted_blocks)
        self.free_blocks = int(free_blocks)
        msg = (f"KV cache exhausted: want {self.wanted_blocks} block(s), "
               f"{self.free_blocks} free")
        if detail:
            msg += f" — {detail}"
        super().__init__(msg)


class BlockedAllocator:
    """Free-list allocator over ``num_blocks`` KV blocks (reference
    ``blocked_allocator.py`` — the linked-list becomes a python set; the
    device never sees this object)."""

    def __init__(self, num_blocks):
        self.num_blocks = int(num_blocks)
        self._free = set(range(self.num_blocks))

    @property
    def free_blocks(self):
        return len(self._free)

    def allocate(self, n):
        if n > len(self._free):
            raise KVCacheExhausted(n, len(self._free))
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, blocks):
        for b in blocks:
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.add(b)


@dataclass
class DSSequenceDescriptor:
    """Host-side record of one tracked sequence (reference
    ``sequence_descriptor.py``)."""
    uid: int
    slot: int                       # row in the block table
    tokens: List[int] = field(default_factory=list)  # full token history
    seen_tokens: int = 0            # tokens already in the KV cache
    blocks: List[int] = field(default_factory=list)
    done: bool = False

    @property
    def cur_length(self):
        return len(self.tokens)

    def pending(self):
        """Token ids not yet through the model."""
        return self.tokens[self.seen_tokens:]


class BlockedKVCache:
    """Paged KV storage (reference ``kv_cache.py``): one jnp array
    ``[L, 2, num_blocks, block_size, Hkv, Dh]`` + the allocator.

    With ``kv_dtype`` set ("int8"/"fp8" — ``kv_codec.py``), the cache is the
    quantized-serving layout instead: ``data`` holds the same shape in the
    narrow storage dtype and ``scales`` holds one f32 per (layer, k/v,
    block, position, kv-head) row — the pair travels through the jitted
    ragged step as one ``(data, scales)`` pytree."""

    def __init__(self, num_layers, num_blocks, block_size, num_kv_heads,
                 head_dim, dtype=jnp.bfloat16, kv_dtype=None):
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.kv_dtype = kv_dtype
        shape = (num_layers, 2, num_blocks, block_size, num_kv_heads,
                 head_dim)
        if kv_dtype is None:
            self.data = jnp.zeros(shape, dtype=dtype)
            self.scales = None
        else:
            from .kv_codec import storage_dtype
            self.data = jnp.zeros(shape, dtype=storage_dtype(kv_dtype))
            # scale=1 for never-written positions keeps dequant a no-op on
            # the zero payload (garbage block included)
            self.scales = jnp.ones(shape[:5], dtype=jnp.float32)
        self.allocator = BlockedAllocator(num_blocks)
        # block 0 is the garbage sink: padding tokens in the ragged buffer
        # scatter their K/V there (their slot-0 block-table row is all zeros)
        self.allocator._free.discard(0)

    def blocks_for(self, num_tokens):
        return -(-num_tokens // self.block_size)


class DSStateManager:
    """Tracks sequences ↔ cache blocks (reference ``ragged_manager.py:19``:
    get_or_create_sequence, flush)."""

    def __init__(self, config, kv_cache: BlockedKVCache):
        self.config = config
        self.kv_cache = kv_cache
        self.max_seqs = int(config.max_ragged_sequence_count)
        self.max_blocks_per_seq = -(-int(config.max_context) //
                                    kv_cache.block_size)
        self._seqs: Dict[int, DSSequenceDescriptor] = {}
        # slot 0 is reserved for padding tokens (its block-table row stays
        # zero, pointing at the garbage block)
        self._free_slots = list(range(1, self.max_seqs))
        # host-side mirror of the device block table
        self.block_table = np.zeros((self.max_seqs, self.max_blocks_per_seq),
                                    dtype=np.int32)

    # ------------------------------------------------------------- tracking
    @property
    def tracked_sequences(self):
        return dict(self._seqs)

    def get_sequence(self, uid) -> Optional[DSSequenceDescriptor]:
        return self._seqs.get(uid)

    def get_or_create_sequence(self, uid) -> DSSequenceDescriptor:
        seq = self._seqs.get(uid)
        if seq is not None:
            return seq
        if not self._free_slots:
            raise RuntimeError("max_ragged_sequence_count exceeded")
        seq = DSSequenceDescriptor(uid=uid, slot=self._free_slots.pop(0))
        self._seqs[uid] = seq
        return seq

    def ensure_capacity(self, seq: DSSequenceDescriptor, total_tokens):
        """Grow the sequence's block list to hold ``total_tokens``."""
        need = self.kv_cache.blocks_for(total_tokens)
        if need > self.max_blocks_per_seq:
            raise RuntimeError(
                f"sequence {seq.uid} exceeds max_context "
                f"({total_tokens} tokens > "
                f"{self.max_blocks_per_seq * self.kv_cache.block_size})")
        while len(seq.blocks) < need:
            blk = self.kv_cache.allocator.allocate(1)[0]
            self.block_table[seq.slot, len(seq.blocks)] = blk
            seq.blocks.append(blk)

    def schedulable_tokens(self, seq: DSSequenceDescriptor, want_total):
        """How many of the tokens up to ``want_total`` can be scheduled with
        the blocks this sequence holds plus the allocator's free pool (the
        reference scheduler's can-schedule check — a sequence the pool
        cannot grow defers instead of crashing the engine step).  Raises
        only for the max_context user error."""
        if self.kv_cache.blocks_for(want_total) > self.max_blocks_per_seq:
            raise RuntimeError(
                f"sequence {seq.uid} exceeds max_context "
                f"({want_total} tokens > "
                f"{self.max_blocks_per_seq * self.kv_cache.block_size})")
        affordable = ((len(seq.blocks) + self.free_blocks)
                      * self.kv_cache.block_size)
        return max(0, min(want_total, affordable) - seq.seen_tokens)

    def flush_sequence(self, uid):
        """Release a sequence (reference ``flush``)."""
        seq = self._seqs.pop(uid, None)
        if seq is None:
            return
        if seq.blocks:
            self.kv_cache.allocator.free(seq.blocks)
        self.block_table[seq.slot, :] = 0
        self._free_slots.append(seq.slot)

    @property
    def free_blocks(self):
        return self.kv_cache.allocator.free_blocks
