"""Per-architecture model builders for inference v2 (reference
``inference/v2/model_implementations/``: llama_v2, mistral, mixtral, qwen_v2
policy/container classes).

TPU redesign: instead of layer containers that map checkpoint params onto
kernel atoms, each builder turns a checkpoint engine's ``(name, array)``
stream into the flax param tree of the matching in-repo model (Llama family
or Mixtral) — the ragged forward in ``ragged_forward.py`` then serves it.
"""

from .hf_builders import (SUPPORTED_MODEL_TYPES, build_model_and_params)
