"""HF checkpoint → flax param tree builders (reference
``inference/v2/model_implementations/*/`` policy+container classes, e.g.
``llama_v2/policy.py``; the name mapping below replaces the reference's
layer-container atom maps).

Supported ``model_type``s: llama, mistral, qwen2 (Llama arch), mixtral
(sparse MoE).  Torch linear weights are [out, in] — flax kernels are
[in, out] — so every projection transposes; attention projections reshape to
the model's [D, H, Dh] head layout.
"""

from typing import Dict, Iterable, Tuple

import numpy as np

from ....models.falcon import FalconConfig, FalconModel
from ....models.llama import LlamaConfig, LlamaModel
from ....models.mixtral import MixtralConfig, MixtralModel
from ....models.opt import OPTConfig, OPTModel
from ....models.phi import PhiConfig, PhiModel
from ....utils.logging import logger

SUPPORTED_MODEL_TYPES = ("llama", "mistral", "qwen2", "mixtral", "phi3",
                         "falcon", "opt", "phi", "qwen2_moe", "qwen",
                         "bloom", "gpt_neox", "gptj", "bert",
                         "gpt_neo", "gpt2", "distilbert")

# ingestable for v1 kernel-injection serving only — no ragged (v2) forward
V1_ONLY_MODEL_TYPES = ("bloom", "gpt_neox", "gptj", "bert",
                       "gpt_neo", "gpt2", "distilbert")

_SKIP_SUFFIXES = (".rotary_emb.inv_freq", ".masked_bias", ".attn.bias")


def _rope_scaling_type(cfg: dict) -> str:
    """The HF rope_scaling type, handling both key spellings ('rope_type'
    new, 'type' old); 'none' when absent."""
    rs = cfg.get("rope_scaling") or {}
    return rs.get("rope_type", rs.get("type", "none")) or "none"


def _rope_scaling_fields(cfg: dict) -> dict:
    """Map HF ``rope_scaling`` onto LlamaConfig's scalar fields.

    Supported: linear, llama3 (Llama-3.1+).  Anything else (longrope/yarn/
    dynamic — e.g. Phi-3 128k) raises rather than silently serving with
    unscaled RoPE and garbage logits."""
    rs = cfg.get("rope_scaling") or {}
    stype = _rope_scaling_type(cfg)
    if stype in ("none", "default"):
        return {}
    if stype == "linear":
        return {"rope_scaling_type": "linear",
                "rope_scaling_factor": float(rs["factor"])}
    if stype == "llama3":
        return {
            "rope_scaling_type": "llama3",
            "rope_scaling_factor": float(rs["factor"]),
            "rope_low_freq_factor": float(rs.get("low_freq_factor", 1.0)),
            "rope_high_freq_factor": float(rs.get("high_freq_factor", 4.0)),
            "rope_original_max_position":
                int(rs.get("original_max_position_embeddings", 8192)),
        }
    raise ValueError(
        f"unsupported rope_scaling type {stype!r} "
        f"({cfg.get('model_type')}): only linear/llama3 are implemented")


def _llama_config_from_hf(cfg: dict, dtype: str) -> LlamaConfig:
    return LlamaConfig(
        **_rope_scaling_fields(cfg),
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        intermediate_size=cfg["intermediate_size"],
        num_hidden_layers=cfg["num_hidden_layers"],
        num_attention_heads=cfg["num_attention_heads"],
        num_key_value_heads=cfg.get("num_key_value_heads",
                                    cfg["num_attention_heads"]),
        max_position_embeddings=cfg.get("max_position_embeddings", 4096),
        rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
        rope_theta=cfg.get("rope_theta", 10000.0),
        tie_word_embeddings=cfg.get("tie_word_embeddings", False),
        sliding_window=cfg.get("sliding_window") or 0,
        attention_bias=cfg.get("attention_bias",
                               cfg.get("model_type") == "qwen2"),
        dtype=dtype, remat=False)


def _mixtral_config_from_hf(cfg: dict, dtype: str) -> MixtralConfig:
    base = _llama_config_from_hf(cfg, dtype)
    from dataclasses import asdict
    return MixtralConfig(
        **asdict(base),
        num_local_experts=cfg.get("num_local_experts", 8),
        num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
        router_aux_loss_coef=cfg.get("router_aux_loss_coef", 0.02))


def _set(tree: dict, path: Tuple[str, ...], value):
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


def _attn_param(arr, key, H, Dh, out_name="o_proj"):
    """q/k/v/output torch weights → DenseGeneral kernels/biases.

    ``out_name`` is the architecture's output-projection name (llama
    ``o_proj``, phi ``dense``, opt ``out_proj``)."""
    proj, kind = key.split(".", 1)      # proj, weight|bias
    if proj == out_name:                # weight [D, H*Dh] → [H*Dh, D]
        if kind == "weight":
            return (proj, "kernel"), np.ascontiguousarray(arr.T)
        return (proj, "bias"), arr
    if kind == "bias":                  # [H*Dh] → [H, Dh]
        return (proj, "bias"), arr.reshape(H, Dh)
    D = arr.shape[1]                    # weight [H*Dh, D] → [D, H, Dh]
    return (proj, "kernel"), np.ascontiguousarray(arr.T).reshape(D, H, Dh)


def _ingest_llama(model_cfg: LlamaConfig,
                  params_iter: Iterable[Tuple[str, np.ndarray]]) -> dict:
    H, Hkv, Dh = (model_cfg.num_attention_heads,
                  model_cfg.num_key_value_heads, model_cfg.head_dim)
    tree: Dict = {}
    for name, arr in params_iter:
        if name.endswith(_SKIP_SUFFIXES):
            continue
        if name == "lm_head.weight":
            if not model_cfg.tie_word_embeddings:
                _set(tree, ("lm_head", "kernel"),
                     np.ascontiguousarray(arr.T))
            continue
        name = name.removeprefix("model.")
        if name == "embed_tokens.weight":
            _set(tree, ("embed_tokens", "embedding"), arr)
        elif name == "norm.weight":
            _set(tree, ("norm", "weight"), arr)
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            layer = f"layers_{idx}"
            if rest.startswith("self_attn."):
                key = rest.removeprefix("self_attn.")
                heads = H if key.startswith(("q_", "o_")) else Hkv
                sub, value = _attn_param(arr, key, heads, Dh)
                _set(tree, (layer, "self_attn") + sub, value)
            elif rest.startswith("mlp."):
                proj = rest.split(".")[1]   # gate_proj|up_proj|down_proj
                _set(tree, (layer, "mlp", proj, "kernel"),
                     np.ascontiguousarray(arr.T))
            elif rest in ("input_layernorm.weight",
                          "post_attention_layernorm.weight"):
                _set(tree, (layer, rest.split(".")[0], "weight"), arr)
            else:
                logger.warning(f"HF llama ingest: skipping {name}")
        else:
            logger.warning(f"HF llama ingest: skipping {name}")
    return tree


def _ingest_mixtral(model_cfg: MixtralConfig,
                    params_iter: Iterable[Tuple[str, np.ndarray]]) -> dict:
    """Llama mapping + block_sparse_moe → stacked-expert ``moe`` params."""
    E, D, I = (model_cfg.num_local_experts, model_cfg.hidden_size,
               model_cfg.intermediate_size)
    passthrough = []
    stacks: Dict[Tuple[str, str], np.ndarray] = {}

    def route():
        for name, arr in params_iter:
            if ".block_sparse_moe." not in name:
                passthrough.append((name, arr))
                continue
            prefix, rest = name.split(".block_sparse_moe.", 1)
            layer = f"layers_{prefix.split('.')[-1]}"
            if rest == "gate.weight":    # [E, D] → [D, E]
                yield layer, ("gate",), np.ascontiguousarray(arr.T)
            else:                        # experts.{e}.w{1,2,3}.weight
                _, e, w, _ = rest.split(".")
                shape = (E, I, D) if w == "w2" else (E, D, I)
                stack = stacks.setdefault((layer, w),
                                          np.empty(shape, dtype=arr.dtype))
                stack[int(e)] = arr.T
                continue

    tree: Dict = {}
    for layer, sub, value in route():
        _set(tree, (layer, "moe", ) + sub + ("kernel", ), value)
    for (layer, w), stack in stacks.items():
        _set(tree, (layer, "moe", w), stack)
    llama_tree = _ingest_llama(model_cfg, passthrough)
    for layer, sub in llama_tree.items():
        node = tree.setdefault(layer, {})
        node.update(sub)
    return tree


def _qwen2_moe_config_from_hf(cfg: dict, dtype: str) -> MixtralConfig:
    if cfg.get("decoder_sparse_step", 1) != 1 or cfg.get("mlp_only_layers"):
        raise ValueError("qwen2_moe with dense interleaved layers "
                         "(decoder_sparse_step != 1 / mlp_only_layers) is "
                         "not supported")
    base = _llama_config_from_hf(cfg, dtype)
    from dataclasses import asdict
    d = asdict(base)
    d["attention_bias"] = True  # qwen2-moe carries q/k/v biases
    d["intermediate_size"] = cfg["moe_intermediate_size"]
    return MixtralConfig(
        **d,
        num_local_experts=cfg.get("num_experts", 60),
        num_experts_per_tok=cfg.get("num_experts_per_tok", 4),
        router_aux_loss_coef=cfg.get("router_aux_loss_coef", 0.001),
        shared_expert_intermediate_size=cfg.get(
            "shared_expert_intermediate_size", 0),
        norm_topk_prob=cfg.get("norm_topk_prob", False))


def _ingest_qwen2_moe(cfg: MixtralConfig, params_iter) -> dict:
    """qwen2-moe → the MixtralModel tree: per-expert gate/up/down stacks
    plus the dense shared expert and its sigmoid mix gate."""
    shared = []

    def stream():
        for name, arr in params_iter:
            if ".mlp.shared_expert" in name:
                shared.append((name, arr))
            elif ".mlp.experts." in name:
                name2 = (name.replace(".mlp.experts.",
                                      ".block_sparse_moe.experts.")
                         .replace(".gate_proj.weight", ".w1.weight")
                         .replace(".up_proj.weight", ".w3.weight")
                         .replace(".down_proj.weight", ".w2.weight"))
                yield name2, arr
            elif name.endswith(".mlp.gate.weight"):
                yield name.replace(".mlp.gate.",
                                   ".block_sparse_moe.gate."), arr
            else:
                yield name, arr

    tree = _ingest_mixtral(cfg, stream())
    for name, arr in shared:
        parts = name.removeprefix("model.").split(".")
        layer = f"layers_{parts[1]}"
        t = np.ascontiguousarray(arr.T)
        if "shared_expert_gate" in name:
            _set(tree, (layer, "moe", "shared_expert_gate", "kernel"), t)
        else:
            proj = parts[4].split("_")[0]            # gate | up | down
            _set(tree, (layer, "moe", f"shared_{proj}_proj", "kernel"), t)
    return tree


def _opt_config_from_hf(cfg: dict, dtype: str) -> OPTConfig:
    proj_dim = cfg.get("word_embed_proj_dim", cfg["hidden_size"])
    if proj_dim != cfg["hidden_size"]:
        raise ValueError(
            f"OPT word_embed_proj_dim={proj_dim} != hidden_size="
            f"{cfg['hidden_size']} (project_in/out variants like opt-350m "
            "are not supported)")
    return OPTConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        ffn_dim=cfg.get("ffn_dim", 4 * cfg["hidden_size"]),
        num_hidden_layers=cfg["num_hidden_layers"],
        num_attention_heads=cfg["num_attention_heads"],
        max_position_embeddings=cfg.get("max_position_embeddings", 2048),
        do_layer_norm_before=cfg.get("do_layer_norm_before", True),
        tie_word_embeddings=cfg.get("tie_word_embeddings", True),
        dtype=dtype, remat=False)


def _ingest_opt(cfg: OPTConfig,
                params_iter: Iterable[Tuple[str, np.ndarray]]) -> dict:
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    tree: Dict = {}
    for name, arr in params_iter:
        if name == "lm_head.weight":
            if not cfg.tie_word_embeddings:
                _set(tree, ("lm_head", "kernel"), np.ascontiguousarray(arr.T))
            continue
        name = name.removeprefix("model.decoder.")
        if name == "embed_tokens.weight":
            _set(tree, ("embed_tokens", "embedding"), arr)
        elif name == "embed_positions.weight":
            _set(tree, ("embed_positions", "embedding"), arr)
        elif name.startswith("final_layer_norm."):
            _set(tree, ("final_layer_norm",
                        "scale" if name.endswith("weight") else "bias"), arr)
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            layer = f"layers_{idx}"
            if rest.startswith("self_attn."):
                sub = rest.removeprefix("self_attn.")
                proj = sub.split(".", 1)[0]
                if proj not in ("q_proj", "k_proj", "v_proj", "out_proj"):
                    logger.warning(f"HF opt ingest: skipping {name}")
                    continue
                path, value = _attn_param(arr, sub, H, Dh,
                                          out_name="out_proj")
                _set(tree, (layer,) + path, value)
            elif rest.split(".")[0] in ("self_attn_layer_norm",
                                        "final_layer_norm"):
                scope, kind = rest.split(".")
                _set(tree, (layer, scope,
                            "scale" if kind == "weight" else "bias"), arr)
            elif rest.startswith(("fc1", "fc2")):
                proj, kind = rest.split(".")
                val = (np.ascontiguousarray(arr.T) if kind == "weight"
                       else arr)
                _set(tree, (layer, proj,
                            "kernel" if kind == "weight" else "bias"), val)
            else:
                logger.warning(f"HF opt ingest: skipping {name}")
        else:
            logger.warning(f"HF opt ingest: skipping {name}")
    return tree


def _reject_rope_scaling(cfg: dict, arch: str):
    """phi/falcon configs have no scaling fields — reject ANY rope_scaling
    with an arch-accurate message (not the linear/llama3 hint)."""
    stype = _rope_scaling_type(cfg)
    if stype not in ("none", "default"):
        raise ValueError(f"rope_scaling ({stype!r}) is not supported for "
                         f"{arch}")


def _phi_config_from_hf(cfg: dict, dtype: str) -> PhiConfig:
    _reject_rope_scaling(cfg, "phi")
    return PhiConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        intermediate_size=cfg["intermediate_size"],
        num_hidden_layers=cfg["num_hidden_layers"],
        num_attention_heads=cfg["num_attention_heads"],
        num_key_value_heads=cfg.get("num_key_value_heads",
                                    cfg["num_attention_heads"]),
        max_position_embeddings=cfg.get("max_position_embeddings", 2048),
        layer_norm_eps=cfg.get("layer_norm_eps", 1e-5),
        rope_theta=cfg.get("rope_theta", 10000.0),
        partial_rotary_factor=cfg.get("partial_rotary_factor", 0.4),
        tie_word_embeddings=cfg.get("tie_word_embeddings", False),
        dtype=dtype, remat=False)


def _ingest_phi(cfg: PhiConfig,
                params_iter: Iterable[Tuple[str, np.ndarray]]) -> dict:
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    tree: Dict = {}
    for name, arr in params_iter:
        if name.endswith(_SKIP_SUFFIXES):  # e.g. persisted rotary inv_freq
            continue
        if name.startswith("lm_head."):
            if not cfg.tie_word_embeddings:
                _set(tree, ("lm_head", "kernel" if name.endswith("weight")
                            else "bias"),
                     np.ascontiguousarray(arr.T) if name.endswith("weight")
                     else arr)
            elif name.endswith("bias"):
                # tying shares only the weight; the bias stays live
                _set(tree, ("lm_head_bias",), arr)
            continue
        name = name.removeprefix("model.")
        if name == "embed_tokens.weight":
            _set(tree, ("embed_tokens", "embedding"), arr)
        elif name.startswith("final_layernorm."):
            _set(tree, ("final_layernorm",
                        "scale" if name.endswith("weight") else "bias"), arr)
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            layer = f"layers_{idx}"
            if rest.startswith("self_attn."):
                sub = rest.removeprefix("self_attn.")
                proj = sub.split(".", 1)[0]
                if proj not in ("q_proj", "k_proj", "v_proj", "dense"):
                    logger.warning(f"HF phi ingest: skipping {name}")
                    continue
                heads = H if proj in ("q_proj", "dense") else Hkv
                path, value = _attn_param(arr, sub, heads, Dh,
                                          out_name="dense")
                _set(tree, (layer,) + path, value)
            elif rest.startswith("mlp."):
                proj, kind = rest.split(".")[1:]
                val = (np.ascontiguousarray(arr.T) if kind == "weight"
                       else arr)
                _set(tree, (layer, proj,
                            "kernel" if kind == "weight" else "bias"), val)
            elif rest.startswith("input_layernorm."):
                _set(tree, (layer, "input_layernorm",
                            "scale" if rest.endswith("weight") else "bias"),
                     arr)
            else:
                logger.warning(f"HF phi ingest: skipping {name}")
        else:
            logger.warning(f"HF phi ingest: skipping {name}")
    return tree


def _split_phi3_fused(params_iter, cfg: LlamaConfig):
    """Phi-3 is the Llama architecture with FUSED projections
    (``qkv_proj`` = [q;k;v], ``gate_up_proj`` = [gate;up], reference
    ``model_implementations/phi3``): split them back into the llama naming
    and let the llama ingest handle the rest."""
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    I = cfg.intermediate_size
    for name, arr in params_iter:
        if name.endswith("self_attn.qkv_proj.weight"):
            base = name.replace("qkv_proj", "{}")
            q, k, v = np.split(arr, [H * Dh, H * Dh + Hkv * Dh], axis=0)
            yield base.format("q_proj"), q
            yield base.format("k_proj"), k
            yield base.format("v_proj"), v
        elif name.endswith("mlp.gate_up_proj.weight"):
            base = name.replace("gate_up_proj", "{}")
            gate, up = np.split(arr, [I], axis=0)
            yield base.format("gate_proj"), gate
            yield base.format("up_proj"), up
        else:
            yield name, arr


def _qwen_config_from_hf(cfg: dict, dtype: str) -> LlamaConfig:
    """Qwen v1 (reference ``model_implementations/qwen/``): the llama
    architecture with a fused biased ``c_attn``, no GQA, and a split MLP
    whose config ``intermediate_size`` counts BOTH halves (w1/w2 are each
    half that width)."""
    if _rope_scaling_type(cfg) not in ("none", "default"):
        raise ValueError("rope_scaling is not supported for qwen v1")
    if cfg.get("use_dynamic_ntk") or cfg.get("use_logn_attn"):
        # official Qwen-7B/14B enable these for long contexts; serving
        # without them silently degrades past seq_length — refuse instead
        raise ValueError(
            "qwen v1 with use_dynamic_ntk/use_logn_attn is not supported "
            "(disable both in config.json to serve within seq_length)")
    if not cfg.get("no_bias", True):
        raise ValueError("qwen v1 with no_bias=false (biased mlp/output "
                         "projections) is not supported")
    return LlamaConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        intermediate_size=cfg["intermediate_size"] // 2,
        num_hidden_layers=cfg["num_hidden_layers"],
        num_attention_heads=cfg["num_attention_heads"],
        num_key_value_heads=cfg["num_attention_heads"],
        max_position_embeddings=cfg.get("seq_length", 2048),
        rms_norm_eps=cfg.get("layer_norm_epsilon", 1e-6),
        rope_theta=cfg.get("rotary_emb_base", 10000.0),
        attention_bias=True,       # only c_attn carries a bias (no_bias
        tie_word_embeddings=False,  # covers every other linear)
        dtype=dtype, remat=False)


def _ingest_qwen(cfg: LlamaConfig,
                 params_iter: Iterable[Tuple[str, np.ndarray]]):
    """Rename/split the Qwen v1 layout into llama names and defer to
    :func:`_ingest_llama`: ``c_attn`` [3D, D] splits to q/k/v (with bias),
    ``mlp.w2`` is the gate (silu side), ``mlp.w1`` the up projection."""
    D = cfg.hidden_size

    def gen():
        for name, arr in params_iter:
            if name.endswith(_SKIP_SUFFIXES) or ".rotary_emb." in name:
                continue
            name = name.removeprefix("transformer.")
            if name == "wte.weight":
                yield "model.embed_tokens.weight", arr
            elif name == "ln_f.weight":
                yield "model.norm.weight", arr
            elif name == "lm_head.weight":
                yield "lm_head.weight", arr
            elif name.startswith("h."):
                _, idx, rest = name.split(".", 2)
                base = f"model.layers.{idx}"
                if rest == "ln_1.weight":
                    yield f"{base}.input_layernorm.weight", arr
                elif rest == "ln_2.weight":
                    yield f"{base}.post_attention_layernorm.weight", arr
                elif rest.startswith("attn.c_attn."):
                    kind = rest.rsplit(".", 1)[1]
                    for proj, part in zip(("q_proj", "k_proj", "v_proj"),
                                          np.split(arr, 3, axis=0)):
                        yield f"{base}.self_attn.{proj}.{kind}", part
                elif rest.startswith(("attn.c_proj.", "mlp.w1.", "mlp.w2.",
                                      "mlp.c_proj.")):
                    src, kind = rest.rsplit(".", 1)
                    if kind != "weight":
                        # config guard rejects no_bias=false; any stray
                        # bias here must not masquerade as a kernel
                        logger.warning(f"HF qwen ingest: skipping {name}")
                        continue
                    target = {"attn.c_proj": "self_attn.o_proj",
                              "mlp.w2": "mlp.gate_proj",  # silu side
                              "mlp.w1": "mlp.up_proj",
                              "mlp.c_proj": "mlp.down_proj"}[src]
                    yield f"{base}.{target}.weight", arr
                else:
                    logger.warning(f"HF qwen ingest: skipping {name}")
            else:
                logger.warning(f"HF qwen ingest: skipping {name}")

    return _ingest_llama(cfg, gen())


def _fused_block_layer_entry(tree, layer, rest, arr, proj_names, ln_names,
                             arch):
    """Shared per-layer dispatch for the bloom/gpt-neox style layouts:
    LayerNorms → scale/bias, listed projections → transposed kernel/bias."""
    proj, kind = rest.rsplit(".", 1)
    if proj in ln_names:
        _set(tree, (layer, proj, "scale" if kind == "weight" else "bias"),
             arr)
    elif proj in proj_names:
        val = np.ascontiguousarray(arr.T) if kind == "weight" else arr
        _set(tree, (layer, proj, "kernel" if kind == "weight" else "bias"),
             val)
    else:
        logger.warning(f"HF {arch} ingest: skipping {layer}.{rest}")


def _bloom_config_from_hf(cfg: dict, dtype: str):
    from ....models.bloom import BloomConfig
    return BloomConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg.get("hidden_size", cfg.get("n_embed")),
        num_hidden_layers=cfg.get("n_layer", cfg.get("num_hidden_layers")),
        num_attention_heads=cfg.get("n_head",
                                    cfg.get("num_attention_heads")),
        layer_norm_epsilon=cfg.get("layer_norm_epsilon", 1e-5),
        apply_residual_connection_post_layernorm=cfg.get(
            "apply_residual_connection_post_layernorm", False),
        dtype=dtype, remat=False)


def _ingest_bloom(cfg, params_iter: Iterable[Tuple[str, np.ndarray]]):
    """HF bloom layout → flax tree.  The fused head-interleaved
    ``query_key_value`` is kept AS-IS (the flax block reshapes the same
    way), so every weight is a plain transpose."""
    tree: Dict = {}
    ln_names = ("input_layernorm", "post_attention_layernorm")
    for name, arr in params_iter:
        if name.endswith(_SKIP_SUFFIXES):
            continue
        name = name.removeprefix("transformer.")
        if name.startswith("word_embeddings_layernorm."):
            kind = name.rsplit(".", 1)[1]
            _set(tree, ("word_embeddings_layernorm",
                        "scale" if kind == "weight" else "bias"), arr)
        elif name == "word_embeddings.weight":
            _set(tree, ("word_embeddings", "embedding"), arr)
        elif name.startswith("ln_f."):
            kind = name.rsplit(".", 1)[1]
            _set(tree, ("ln_f", "scale" if kind == "weight" else "bias"),
                 arr)
        elif name == "lm_head.weight":
            continue  # always tied to word_embeddings
        elif name.startswith("h."):
            _, idx, rest = name.split(".", 2)
            layer = f"h_{idx}"
            rest = rest.removeprefix("self_attention.")                        .removeprefix("mlp.")
            proj, kind = rest.rsplit(".", 1)
            if proj in ln_names:
                _set(tree, (layer, proj,
                            "scale" if kind == "weight" else "bias"), arr)
            elif proj in ("query_key_value", "dense", "dense_h_to_4h",
                          "dense_4h_to_h"):
                val = (np.ascontiguousarray(arr.T) if kind == "weight"
                       else arr)
                _set(tree, (layer, proj,
                            "kernel" if kind == "weight" else "bias"), val)
            else:
                logger.warning(f"HF bloom ingest: skipping {name}")
        else:
            logger.warning(f"HF bloom ingest: skipping {name}")
    return tree


def _gpt_neox_config_from_hf(cfg: dict, dtype: str):
    from ....models.gpt_neox import GPTNeoXConfig
    _reject_rope_scaling(cfg, "gpt_neox")
    return GPTNeoXConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        intermediate_size=cfg.get("intermediate_size",
                                  4 * cfg["hidden_size"]),
        num_hidden_layers=cfg["num_hidden_layers"],
        num_attention_heads=cfg["num_attention_heads"],
        max_position_embeddings=cfg.get("max_position_embeddings", 2048),
        rotary_pct=cfg.get("rotary_pct", 0.25),
        rotary_emb_base=cfg.get("rotary_emb_base",
                                cfg.get("rope_theta", 10000.0)),
        layer_norm_eps=cfg.get("layer_norm_eps", 1e-5),
        use_parallel_residual=cfg.get("use_parallel_residual", True),
        hidden_act=cfg.get("hidden_act", "gelu"),
        dtype=dtype, remat=False)


def _ingest_gpt_neox(cfg, params_iter: Iterable[Tuple[str, np.ndarray]]):
    """HF gpt-neox → flax: the fused head-interleaved ``query_key_value``
    is kept as-is (the flax block reshapes identically); every weight is a
    plain transpose."""
    tree: Dict = {}
    proj_names = ("query_key_value", "dense", "dense_h_to_4h",
                  "dense_4h_to_h")
    ln_names = ("input_layernorm", "post_attention_layernorm")
    for name, arr in params_iter:
        if name.endswith(_SKIP_SUFFIXES) or ".attention.bias" in name \
                or ".rotary_emb." in name or ".masked_bias" in name:
            continue
        name = name.removeprefix("gpt_neox.")
        if name == "embed_in.weight":
            _set(tree, ("embed_in", "embedding"), arr)
        elif name == "embed_out.weight":
            _set(tree, ("embed_out", "kernel"), np.ascontiguousarray(arr.T))
        elif name.startswith("final_layer_norm."):
            kind = name.rsplit(".", 1)[1]
            _set(tree, ("final_layer_norm",
                        "scale" if kind == "weight" else "bias"), arr)
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            rest = rest.removeprefix("attention.").removeprefix("mlp.")
            _fused_block_layer_entry(tree, f"layers_{idx}", rest, arr,
                                     proj_names=proj_names,
                                     ln_names=ln_names, arch="gpt_neox")
        else:
            logger.warning(f"HF gpt_neox ingest: skipping {name}")
    return tree


def _gptj_rotary_dim(cfg: dict) -> int:
    rd = cfg.get("rotary_dim", 64)
    if rd is None:
        # HF's null-rotary path builds the sincos table at embed_dim, a
        # different frequency progression than head_dim — every released
        # GPT-J checkpoint sets rotary_dim, so refuse rather than serve a
        # subtly different rotation
        raise ValueError("gptj with rotary_dim=null is not supported "
                         "(set an explicit rotary_dim)")
    return int(rd)


def _gptj_config_from_hf(cfg: dict, dtype: str):
    from ....models.gptj import GPTJConfig
    _reject_rope_scaling(cfg, "gptj")
    return GPTJConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg.get("n_embd", cfg.get("hidden_size")),
        num_hidden_layers=cfg.get("n_layer", cfg.get("num_hidden_layers")),
        num_attention_heads=cfg.get("n_head",
                                    cfg.get("num_attention_heads")),
        rotary_dim=_gptj_rotary_dim(cfg),
        intermediate_size=cfg.get("n_inner")
        or 4 * cfg.get("n_embd", cfg.get("hidden_size")),
        max_position_embeddings=cfg.get("n_positions", 2048),
        layer_norm_epsilon=cfg.get("layer_norm_epsilon", 1e-5),
        dtype=dtype, remat=False)


def _ingest_gptj(cfg, params_iter: Iterable[Tuple[str, np.ndarray]]):
    """HF gptj → flax (separate unbiased q/k/v/out; one shared ln_1)."""
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    tree: Dict = {}
    for name, arr in params_iter:
        if name.endswith(_SKIP_SUFFIXES):
            continue
        if name.startswith("lm_head."):
            kind = name.rsplit(".", 1)[1]
            _set(tree, ("lm_head", "kernel" if kind == "weight" else "bias"),
                 np.ascontiguousarray(arr.T) if kind == "weight" else arr)
            continue
        name = name.removeprefix("transformer.")
        if name == "wte.weight":
            _set(tree, ("wte", "embedding"), arr)
        elif name.startswith("ln_f."):
            kind = name.rsplit(".", 1)[1]
            _set(tree, ("ln_f", "scale" if kind == "weight" else "bias"),
                 arr)
        elif name.startswith("h."):
            _, idx, rest = name.split(".", 2)
            layer = f"h_{idx}"
            if rest.startswith("ln_1."):
                kind = rest.rsplit(".", 1)[1]
                _set(tree, (layer, "ln_1",
                            "scale" if kind == "weight" else "bias"), arr)
            elif rest.startswith("attn."):
                sub = rest.removeprefix("attn.")
                proj = sub.split(".", 1)[0]
                if proj not in ("q_proj", "k_proj", "v_proj", "out_proj"):
                    logger.warning(f"HF gptj ingest: skipping {name}")
                    continue
                path, value = _attn_param(arr, sub, H, Dh,
                                          out_name="out_proj")
                _set(tree, (layer, ) + path, value)
            elif rest.startswith("mlp."):
                proj, kind = rest.removeprefix("mlp.").rsplit(".", 1)
                val = (np.ascontiguousarray(arr.T) if kind == "weight"
                       else arr)
                _set(tree, (layer, proj,
                            "kernel" if kind == "weight" else "bias"), val)
            else:
                logger.warning(f"HF gptj ingest: skipping {name}")
        else:
            logger.warning(f"HF gptj ingest: skipping {name}")
    return tree


def _bert_config_from_hf(cfg: dict, dtype: str):
    from ....models.bert import BertConfig
    if cfg.get("hidden_act", "gelu") != "gelu":
        raise ValueError(f"bert hidden_act {cfg.get('hidden_act')!r} is "
                         "not supported (erf gelu only)")
    archs = cfg.get("architectures") or []
    if archs and not any("ForMaskedLM" in a for a in archs):
        raise ValueError(
            f"bert checkpoint architectures {archs} carry no MLM head — "
            "only BertForMaskedLM checkpoints are servable (the encoder "
            "head weights cls.predictions.* are required)")
    return BertConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        num_hidden_layers=cfg["num_hidden_layers"],
        num_attention_heads=cfg["num_attention_heads"],
        intermediate_size=cfg["intermediate_size"],
        max_position_embeddings=cfg.get("max_position_embeddings", 512),
        type_vocab_size=cfg.get("type_vocab_size", 2),
        layer_norm_eps=cfg.get("layer_norm_eps", 1e-12),
        mlm_transform=True, dtype=dtype, remat=False)


def _ingest_bert(cfg, params_iter: Iterable[Tuple[str, np.ndarray]]):
    """HF BertForMaskedLM → flax (MLM transform head mapped onto
    mlm_dense/mlm_ln/mlm_bias; decoder weight is tied to the word
    embeddings and skipped)."""
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    tree: Dict = {}
    for name, arr in params_iter:
        if name.endswith(_SKIP_SUFFIXES) or name.startswith("bert.pooler."):
            continue
        if name.startswith("cls.predictions."):
            rest = name.removeprefix("cls.predictions.")
            if rest == "bias" or rest == "decoder.bias":
                _set(tree, ("mlm_bias", ), arr)
            elif rest == "decoder.weight":
                continue  # tied to word_embeddings
            elif rest.startswith("transform.dense."):
                kind = rest.rsplit(".", 1)[1]
                _set(tree, ("mlm_dense",
                            "kernel" if kind == "weight" else "bias"),
                     np.ascontiguousarray(arr.T) if kind == "weight"
                     else arr)
            elif rest.startswith("transform.LayerNorm."):
                kind = rest.rsplit(".", 1)[1]
                _set(tree, ("mlm_ln",
                            "scale" if kind == "weight" else "bias"), arr)
            else:
                logger.warning(f"HF bert ingest: skipping {name}")
            continue
        name = name.removeprefix("bert.")
        if name.startswith("embeddings."):
            rest = name.removeprefix("embeddings.")
            base = rest.rsplit(".", 1)[0]
            if base in ("word_embeddings", "position_embeddings",
                        "token_type_embeddings"):
                _set(tree, (base, "embedding"), arr)
            elif base == "LayerNorm":
                kind = rest.rsplit(".", 1)[1]
                _set(tree, ("embeddings_ln",
                            "scale" if kind == "weight" else "bias"), arr)
            else:
                logger.warning(f"HF bert ingest: skipping {name}")
        elif name.startswith("encoder.layer."):
            _, _, idx, rest = name.split(".", 3)
            layer = f"layer_{idx}"
            kind = rest.rsplit(".", 1)[1]
            if rest.startswith("attention.self."):
                proj = rest.split(".")[2]     # query|key|value
                if kind == "weight":
                    D = arr.shape[1]
                    _set(tree, (layer, proj, "kernel"),
                         np.ascontiguousarray(arr.T).reshape(D, H, Dh))
                else:
                    _set(tree, (layer, proj, "bias"), arr.reshape(H, Dh))
            elif rest.startswith("attention.output.dense."):
                if kind == "weight":           # [D, D] → [H, Dh, D]
                    D = arr.shape[0]
                    _set(tree, (layer, "attention_output", "kernel"),
                         np.ascontiguousarray(arr.T).reshape(H, Dh, D))
                else:
                    _set(tree, (layer, "attention_output", "bias"), arr)
            elif rest.startswith("attention.output.LayerNorm."):
                _set(tree, (layer, "attention_ln",
                            "scale" if kind == "weight" else "bias"), arr)
            elif rest.startswith("intermediate.dense."):
                _set(tree, (layer, "intermediate",
                            "kernel" if kind == "weight" else "bias"),
                     np.ascontiguousarray(arr.T) if kind == "weight"
                     else arr)
            elif rest.startswith("output.dense."):
                _set(tree, (layer, "output",
                            "kernel" if kind == "weight" else "bias"),
                     np.ascontiguousarray(arr.T) if kind == "weight"
                     else arr)
            elif rest.startswith("output.LayerNorm."):
                _set(tree, (layer, "output_ln",
                            "scale" if kind == "weight" else "bias"), arr)
            else:
                logger.warning(f"HF bert ingest: skipping {name}")
        else:
            logger.warning(f"HF bert ingest: skipping {name}")
    # a config.json without an "architectures" list slips past the
    # _bert_config_from_hf guard — re-check on the ingested tree so a
    # headless checkpoint fails HERE with the real reason, not later
    # inside flax apply with an opaque missing-param error
    if "mlm_dense" not in tree or "mlm_bias" not in tree:
        raise ValueError(
            "bert checkpoint carries no MLM head weights "
            "(cls.predictions.*) — only BertForMaskedLM checkpoints are "
            "servable")
    return tree


def _gpt_neo_config_from_hf(cfg: dict, dtype: str):
    from ....models.gpt_neo import GPTNeoConfig
    act = cfg.get("activation_function", "gelu_new")
    if act != "gelu_new":
        raise ValueError(f"gpt_neo activation_function {act!r} is not "
                         "supported (gelu_new only)")
    return GPTNeoConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        num_hidden_layers=cfg.get("num_layers",
                                  cfg.get("num_hidden_layers")),
        num_attention_heads=cfg.get("num_heads",
                                    cfg.get("num_attention_heads")),
        intermediate_size=cfg.get("intermediate_size")
        or 4 * cfg["hidden_size"],
        max_position_embeddings=cfg.get("max_position_embeddings", 2048),
        window_size=cfg.get("window_size", 256),
        attention_layers=tuple(cfg.get("attention_layers",
                                       ["global", "local"])),
        layer_norm_epsilon=cfg.get("layer_norm_epsilon", 1e-5),
        dtype=dtype, remat=False)


def _ingest_gpt_neo(cfg, params_iter: Iterable[Tuple[str, np.ndarray]]):
    """HF gpt-neo → flax (separate unbiased q/k/v under attn.attention,
    biased out_proj/mlp, gpt2-style names, tied head)."""
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    tree: Dict = {}
    for name, arr in params_iter:
        if name.endswith(_SKIP_SUFFIXES) or \
                name.endswith(".attn.attention.bias"):
            # legacy .bin checkpoints persist the causal-mask buffer
            continue
        if name == "lm_head.weight":
            continue  # tied to wte
        name = name.removeprefix("transformer.")
        if name in ("wte.weight", "wpe.weight"):
            _set(tree, (name.split(".")[0], "embedding"), arr)
        elif name.startswith("ln_f."):
            kind = name.rsplit(".", 1)[1]
            _set(tree, ("ln_f", "scale" if kind == "weight" else "bias"),
                 arr)
        elif name.startswith("h."):
            _, idx, rest = name.split(".", 2)
            layer = f"h_{idx}"
            rest = rest.removeprefix("attn.attention.") \
                       .removeprefix("mlp.")
            proj, kind = rest.rsplit(".", 1)
            if proj in ("ln_1", "ln_2"):
                _set(tree, (layer, proj,
                            "scale" if kind == "weight" else "bias"), arr)
            elif proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
                path, value = _attn_param(arr, rest, H, Dh,
                                          out_name="out_proj")
                _set(tree, (layer, ) + path, value)
            elif proj in ("c_fc", "c_proj"):
                val = (np.ascontiguousarray(arr.T) if kind == "weight"
                       else arr)
                _set(tree, (layer, proj,
                            "kernel" if kind == "weight" else "bias"), val)
            else:
                logger.warning(f"HF gpt_neo ingest: skipping {name}")
        else:
            logger.warning(f"HF gpt_neo ingest: skipping {name}")
    return tree


def _falcon_config_from_hf(cfg: dict, dtype: str) -> FalconConfig:
    _reject_rope_scaling(cfg, "falcon")
    if (cfg.get("new_decoder_architecture")
            and cfg.get("num_ln_in_parallel_attn") == 1):
        # falcon-11B layout: one shared pre-layernorm instead of
        # ln_attn/ln_mlp — the model/ragged step read the two-LN layout
        raise ValueError("falcon with new_decoder_architecture and "
                         "num_ln_in_parallel_attn=1 (e.g. falcon-11B) is "
                         "not supported")
    if cfg.get("alibi"):
        raise ValueError("falcon alibi variants are not supported "
                         "(rotary models only)")
    H = cfg["num_attention_heads"]
    if cfg.get("new_decoder_architecture"):
        num_kv = cfg.get("num_kv_heads", H)
    else:
        num_kv = 1 if cfg.get("multi_query", True) else H
    return FalconConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        num_hidden_layers=cfg["num_hidden_layers"],
        num_attention_heads=H,
        num_kv_heads=num_kv,
        ffn_hidden_size=cfg.get("ffn_hidden_size"),
        max_position_embeddings=cfg.get("max_position_embeddings", 2048),
        layer_norm_epsilon=cfg.get("layer_norm_epsilon", 1e-5),
        rope_theta=cfg.get("rope_theta", 10000.0),
        new_decoder_architecture=cfg.get("new_decoder_architecture", False),
        parallel_attn=cfg.get("parallel_attn", True),
        bias=cfg.get("bias", False),
        # HF falcon ties by default and OMITS the key from config.json
        tie_word_embeddings=cfg.get("tie_word_embeddings", True),
        dtype=dtype, remat=False)


def _split_falcon_qkv(arr, cfg: FalconConfig):
    """The fused ``query_key_value`` weight's three layouts (HF
    ``modeling_falcon._split_heads`` semantics): grouped (new arch),
    multi-query (kv tail), or per-head interleaved (old multi-head)."""
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_kv_heads, cfg.head_dim)
    D = arr.shape[-1]
    if cfg.new_decoder_architecture:
        g = H // Hkv
        w = arr.reshape(Hkv, g + 2, Dh, D)
        q = w[:, :g].reshape(H * Dh, D)
        k = w[:, g].reshape(Hkv * Dh, D)
        v = w[:, g + 1].reshape(Hkv * Dh, D)
    elif Hkv == 1:
        q, k, v = np.split(arr, [H * Dh, (H + 1) * Dh], axis=0)
    else:
        w = arr.reshape(H, 3, Dh, D)
        q = w[:, 0].reshape(H * Dh, D)
        k = w[:, 1].reshape(H * Dh, D)
        v = w[:, 2].reshape(H * Dh, D)
    return q, k, v


def _ingest_falcon(cfg: FalconConfig,
                   params_iter: Iterable[Tuple[str, np.ndarray]]) -> dict:
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_kv_heads, cfg.head_dim)
    tree: Dict = {}
    for name, arr in params_iter:
        if name == "lm_head.weight":
            if not cfg.tie_word_embeddings:
                _set(tree, ("lm_head", "kernel"), np.ascontiguousarray(arr.T))
            continue
        name = name.removeprefix("transformer.")
        if name == "word_embeddings.weight":
            _set(tree, ("word_embeddings", "embedding"), arr)
        elif name.startswith("ln_f."):
            _set(tree, ("ln_f", "scale" if name.endswith("weight")
                        else "bias"), arr)
        elif name.startswith("h."):
            _, idx, rest = name.split(".", 2)
            layer = f"h_{idx}"
            if rest == "self_attention.query_key_value.weight":
                q, k, v = _split_falcon_qkv(arr, cfg)
                D = arr.shape[-1]
                _set(tree, (layer, "q_proj", "kernel"),
                     np.ascontiguousarray(q.T).reshape(D, H, Dh))
                _set(tree, (layer, "k_proj", "kernel"),
                     np.ascontiguousarray(k.T).reshape(D, k.shape[0] // Dh,
                                                       Dh))
                _set(tree, (layer, "v_proj", "kernel"),
                     np.ascontiguousarray(v.T).reshape(D, v.shape[0] // Dh,
                                                       Dh))
            elif rest == "self_attention.query_key_value.bias":
                # bias=True variants (falcon-rw): split like the weight
                q, k, v = _split_falcon_qkv(arr[:, None], cfg)
                _set(tree, (layer, "q_proj", "bias"), q.reshape(H, Dh))
                _set(tree, (layer, "k_proj", "bias"),
                     k.reshape(k.shape[0] // Dh, Dh))
                _set(tree, (layer, "v_proj", "bias"),
                     v.reshape(v.shape[0] // Dh, Dh))
            elif rest == "self_attention.dense.weight":
                _set(tree, (layer, "dense", "kernel"),
                     np.ascontiguousarray(arr.T))
            elif rest == "self_attention.dense.bias":
                _set(tree, (layer, "dense", "bias"), arr)
            elif rest.startswith("mlp."):
                proj, kind = rest.split(".")[1:]
                _set(tree, (layer, proj,
                            "kernel" if kind == "weight" else "bias"),
                     np.ascontiguousarray(arr.T) if kind == "weight"
                     else arr)
            elif rest.split(".")[0] in ("input_layernorm", "ln_attn",
                                        "ln_mlp",
                                        "post_attention_layernorm"):
                scope, kind = rest.split(".")
                _set(tree, (layer, scope,
                            "scale" if kind == "weight" else "bias"), arr)
            else:
                logger.warning(f"HF falcon ingest: skipping {name}")
        else:
            logger.warning(f"HF falcon ingest: skipping {name}")
    return tree


def _gpt2_config_from_hf(cfg: dict, dtype: str):
    """HF GPT2Config → GPT2Config (reference container ``containers/gpt2.py``
    HFGPT2LayerPolicy; Conv1D weights are [in, out] — no transpose)."""
    from ....models.gpt2 import GPT2Config
    act = cfg.get("activation_function", "gelu_new")
    if act != "gelu_new":
        # GPT2Block hardcodes the tanh approximation (gelu_new); serving an
        # erf-gelu checkpoint through it would silently diverge
        raise ValueError(f"gpt2 activation_function {act!r} is not "
                         "supported (gelu_new only)")
    n_embd = cfg.get("n_embd", cfg.get("hidden_size"))
    n_inner = cfg.get("n_inner")
    if n_inner is not None and n_inner != 4 * n_embd:
        raise ValueError(
            f"gpt2 n_inner={n_inner} is not supported (the block hardcodes "
            f"the 4*hidden MLP width = {4 * n_embd})")
    if not cfg.get("tie_word_embeddings", True):
        raise ValueError(
            "gpt2 tie_word_embeddings=False is not supported — GPT2Model "
            "projects logits through the word-embedding table")
    return GPT2Config(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg.get("n_embd", cfg.get("hidden_size")),
        num_hidden_layers=cfg.get("n_layer", cfg.get("num_hidden_layers")),
        num_attention_heads=cfg.get("n_head", cfg.get("num_attention_heads")),
        max_position_embeddings=cfg.get("n_positions",
                                        cfg.get("max_position_embeddings",
                                                1024)),
        layer_norm_epsilon=cfg.get("layer_norm_epsilon", 1e-5),
        dtype=dtype, remat=False)


def _ingest_gpt2(cfg, params_iter: Iterable[Tuple[str, np.ndarray]]):
    """HF GPT2LMHeadModel → flax.  Conv1D stores [in, out]; the fused
    c_attn [D, 3D] splits into q/k/v kernels [D, H, Dh]."""
    H, Dh, D = cfg.num_attention_heads, cfg.head_dim, cfg.hidden_size
    tree: Dict = {}
    for name, arr in params_iter:
        name = name.removeprefix("transformer.")
        if name.endswith(_SKIP_SUFFIXES) or name == "lm_head.weight":
            continue  # lm_head is tied to wte
        if name == "wte.weight":
            _set(tree, ("wte", "embedding"), arr)
        elif name == "wpe.weight":
            _set(tree, ("wpe", "embedding"), arr)
        elif name.startswith("ln_f."):
            kind = name.rsplit(".", 1)[1]
            _set(tree, ("ln_f", "scale" if kind == "weight" else "bias"), arr)
        elif name.startswith("h."):
            _, idx, rest = name.split(".", 2)
            layer = f"h_{idx}"
            kind = rest.rsplit(".", 1)[1]
            if rest.startswith("attn.c_attn."):
                if kind == "weight":   # [D, 3D] Conv1D
                    for i, proj in enumerate(("q_proj", "k_proj", "v_proj")):
                        _set(tree, (layer, proj, "kernel"),
                             np.ascontiguousarray(
                                 arr[:, i * D:(i + 1) * D]).reshape(D, H, Dh))
                else:                  # [3D]
                    for i, proj in enumerate(("q_proj", "k_proj", "v_proj")):
                        _set(tree, (layer, proj, "bias"),
                             arr[i * D:(i + 1) * D].reshape(H, Dh))
            elif rest.startswith("attn.c_proj."):
                if kind == "weight":   # [D, D] Conv1D → [H, Dh, D]
                    _set(tree, (layer, "c_proj", "kernel"),
                         np.ascontiguousarray(arr).reshape(H, Dh, D))
                else:
                    _set(tree, (layer, "c_proj", "bias"), arr)
            elif rest.startswith("mlp.c_fc."):
                _set(tree, (layer, "c_fc", "kernel" if kind == "weight"
                            else "bias"), arr)
            elif rest.startswith("mlp.c_proj."):
                _set(tree, (layer, "mlp_proj", "kernel" if kind == "weight"
                            else "bias"), arr)
            elif rest.startswith(("ln_1.", "ln_2.")):
                ln = rest.split(".", 1)[0]
                _set(tree, (layer, ln,
                            "scale" if kind == "weight" else "bias"), arr)
            else:
                logger.warning(f"HF gpt2 ingest: skipping {name}")
        else:
            logger.warning(f"HF gpt2 ingest: skipping {name}")
    return tree


def _distilbert_config_from_hf(cfg: dict, dtype: str):
    """HF DistilBertConfig → BertConfig (reference container
    ``containers/distil_bert.py`` HFDistilBertLayerPolicy).  DistilBERT has
    no token-type embeddings: type_vocab_size=1 with a zero table."""
    from ....models.bert import BertConfig
    if cfg.get("sinusoidal_pos_embds"):
        raise ValueError("distilbert sinusoidal_pos_embds=True is not "
                         "supported (learned positions only)")
    if cfg.get("activation", "gelu") != "gelu":
        raise ValueError(f"distilbert activation "
                         f"{cfg.get('activation')!r} unsupported")
    if not cfg.get("tie_word_embeddings", True):
        raise ValueError(
            "distilbert tie_word_embeddings=False is not supported — the "
            "MLM projector is served through the word-embedding table")
    return BertConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg.get("dim", cfg.get("hidden_size")),
        num_hidden_layers=cfg.get("n_layers", cfg.get("num_hidden_layers")),
        num_attention_heads=cfg.get("n_heads",
                                    cfg.get("num_attention_heads")),
        intermediate_size=cfg.get("hidden_dim",
                                  cfg.get("intermediate_size")),
        max_position_embeddings=cfg.get("max_position_embeddings", 512),
        type_vocab_size=1, layer_norm_eps=1e-12,
        mlm_transform=True, dtype=dtype, remat=False)


def _ingest_distilbert(cfg, params_iter: Iterable[Tuple[str, np.ndarray]]):
    """HF DistilBertForMaskedLM → flax (BertModel layout; the MLM head's
    vocab_transform/vocab_layer_norm/vocab_projector map onto
    mlm_dense/mlm_ln/mlm_bias, projector weight tied to the word
    embeddings)."""
    H, Dh, D = cfg.num_attention_heads, cfg.head_dim, cfg.hidden_size
    tree: Dict = {}
    proj_map = {"q_lin": "query", "k_lin": "key", "v_lin": "value"}
    for name, arr in params_iter:
        name = name.removeprefix("distilbert.")
        kind = name.rsplit(".", 1)[1]
        if name == "vocab_projector.weight":
            continue  # tied to word_embeddings
        if name.startswith("vocab_transform."):
            _set(tree, ("mlm_dense", "kernel" if kind == "weight" else
                        "bias"),
                 np.ascontiguousarray(arr.T) if kind == "weight" else arr)
        elif name.startswith("vocab_layer_norm."):
            _set(tree, ("mlm_ln", "scale" if kind == "weight" else "bias"),
                 arr)
        elif name == "vocab_projector.bias":
            _set(tree, ("mlm_bias", ), arr)
        elif name.startswith("embeddings."):
            base = name.split(".")[1]
            if base in ("word_embeddings", "position_embeddings"):
                _set(tree, (base, "embedding"), arr)
            elif base == "LayerNorm":
                _set(tree, ("embeddings_ln",
                            "scale" if kind == "weight" else "bias"), arr)
            else:
                logger.warning(f"HF distilbert ingest: skipping {name}")
        elif name.startswith("transformer.layer."):
            _, _, idx, rest = name.split(".", 3)
            layer = f"layer_{idx}"
            head = rest.split(".")[0]
            if head == "attention":
                proj = rest.split(".")[1]
                if proj in proj_map:
                    if kind == "weight":
                        _set(tree, (layer, proj_map[proj], "kernel"),
                             np.ascontiguousarray(arr.T).reshape(D, H, Dh))
                    else:
                        _set(tree, (layer, proj_map[proj], "bias"),
                             arr.reshape(H, Dh))
                elif proj == "out_lin":
                    if kind == "weight":
                        _set(tree, (layer, "attention_output", "kernel"),
                             np.ascontiguousarray(arr.T).reshape(H, Dh, D))
                    else:
                        _set(tree, (layer, "attention_output", "bias"), arr)
            elif head == "sa_layer_norm":
                _set(tree, (layer, "attention_ln",
                            "scale" if kind == "weight" else "bias"), arr)
            elif head == "ffn":
                lin = rest.split(".")[1]
                target = "intermediate" if lin == "lin1" else "output"
                _set(tree, (layer, target, "kernel" if kind == "weight"
                            else "bias"),
                     np.ascontiguousarray(arr.T) if kind == "weight"
                     else arr)
            elif head == "output_layer_norm":
                _set(tree, (layer, "output_ln",
                            "scale" if kind == "weight" else "bias"), arr)
            else:
                logger.warning(f"HF distilbert ingest: skipping {name}")
        else:
            logger.warning(f"HF distilbert ingest: skipping {name}")
    # no token-type embeddings in distilbert: a zero table keeps the
    # BertModel forward (which always adds the type embedding) exact
    _set(tree, ("token_type_embeddings", "embedding"),
         np.zeros((1, D), np.float32))
    if "mlm_dense" not in tree or "mlm_bias" not in tree:
        raise ValueError(
            "distilbert checkpoint carries no MLM head weights "
            "(vocab_transform/vocab_projector) — only "
            "DistilBertForMaskedLM checkpoints are servable")
    return tree


def build_model_and_params(checkpoint_engine, dtype: str = "bfloat16"):
    """(model, params) from a checkpoint engine with a ``model_config`` dict
    (HF ``config.json``).  Reference analog: ``engine_factory.build_hf_engine``
    dispatching on ``model_type`` (``engine_factory.py:69``)."""
    hf_cfg = checkpoint_engine.model_config
    model_type = hf_cfg.get("model_type", "llama")
    if model_type not in SUPPORTED_MODEL_TYPES:
        raise ValueError(
            f"unsupported model_type {model_type!r} "
            f"(supported: {SUPPORTED_MODEL_TYPES})")
    if model_type == "mixtral":
        cfg = _mixtral_config_from_hf(hf_cfg, dtype)
        params = _ingest_mixtral(cfg, checkpoint_engine.parameters())
        model = MixtralModel(cfg)
    elif model_type == "qwen2_moe":
        cfg = _qwen2_moe_config_from_hf(hf_cfg, dtype)
        params = _ingest_qwen2_moe(cfg, checkpoint_engine.parameters())
        model = MixtralModel(cfg)
    elif model_type == "falcon":
        cfg = _falcon_config_from_hf(hf_cfg, dtype)
        params = _ingest_falcon(cfg, checkpoint_engine.parameters())
        model = FalconModel(cfg)
    elif model_type == "opt":
        cfg = _opt_config_from_hf(hf_cfg, dtype)
        params = _ingest_opt(cfg, checkpoint_engine.parameters())
        model = OPTModel(cfg)
    elif model_type == "phi":
        cfg = _phi_config_from_hf(hf_cfg, dtype)
        params = _ingest_phi(cfg, checkpoint_engine.parameters())
        model = PhiModel(cfg)
    elif model_type == "qwen":
        cfg = _qwen_config_from_hf(hf_cfg, dtype)
        params = _ingest_qwen(cfg, checkpoint_engine.parameters())
        model = LlamaModel(cfg)
    elif model_type == "bloom":
        from ....models.bloom import BloomModel
        cfg = _bloom_config_from_hf(hf_cfg, dtype)
        params = _ingest_bloom(cfg, checkpoint_engine.parameters())
        model = BloomModel(cfg)
    elif model_type == "gpt_neox":
        from ....models.gpt_neox import GPTNeoXModel
        cfg = _gpt_neox_config_from_hf(hf_cfg, dtype)
        params = _ingest_gpt_neox(cfg, checkpoint_engine.parameters())
        model = GPTNeoXModel(cfg)
    elif model_type == "gptj":
        from ....models.gptj import GPTJModel
        cfg = _gptj_config_from_hf(hf_cfg, dtype)
        params = _ingest_gptj(cfg, checkpoint_engine.parameters())
        model = GPTJModel(cfg)
    elif model_type == "bert":
        from ....models.bert import BertModel
        cfg = _bert_config_from_hf(hf_cfg, dtype)
        params = _ingest_bert(cfg, checkpoint_engine.parameters())
        model = BertModel(cfg)
    elif model_type == "gpt_neo":
        from ....models.gpt_neo import GPTNeoModel
        cfg = _gpt_neo_config_from_hf(hf_cfg, dtype)
        params = _ingest_gpt_neo(cfg, checkpoint_engine.parameters())
        model = GPTNeoModel(cfg)
    elif model_type == "gpt2":
        from ....models.gpt2 import GPT2Model
        cfg = _gpt2_config_from_hf(hf_cfg, dtype)
        params = _ingest_gpt2(cfg, checkpoint_engine.parameters())
        model = GPT2Model(cfg)
    elif model_type == "distilbert":
        from ....models.bert import BertModel
        cfg = _distilbert_config_from_hf(hf_cfg, dtype)
        params = _ingest_distilbert(cfg, checkpoint_engine.parameters())
        model = BertModel(cfg)
    else:
        cfg = _llama_config_from_hf(hf_cfg, dtype)
        source = checkpoint_engine.parameters()
        if model_type == "phi3":
            source = _split_phi3_fused(source, cfg)
        params = _ingest_llama(cfg, source)
        model = LlamaModel(cfg)
    if getattr(cfg, "sliding_window", 0):
        logger.info(f"{model_type}: sliding_window={cfg.sliding_window} "
                    "(enforced in the ragged attention path)")
    return model, params
