"""HF checkpoint → flax param tree builders (reference
``inference/v2/model_implementations/*/`` policy+container classes, e.g.
``llama_v2/policy.py``; the name mapping below replaces the reference's
layer-container atom maps).

Supported ``model_type``s: llama, mistral, qwen2 (Llama arch), mixtral
(sparse MoE).  Torch linear weights are [out, in] — flax kernels are
[in, out] — so every projection transposes; attention projections reshape to
the model's [D, H, Dh] head layout.
"""

from typing import Dict, Iterable, Tuple

import numpy as np

from ....models.llama import LlamaConfig, LlamaModel
from ....models.mixtral import MixtralConfig, MixtralModel
from ....utils.logging import logger

SUPPORTED_MODEL_TYPES = ("llama", "mistral", "qwen2", "mixtral")

_SKIP_SUFFIXES = (".rotary_emb.inv_freq", ".masked_bias", ".attn.bias")


def _llama_config_from_hf(cfg: dict, dtype: str) -> LlamaConfig:
    return LlamaConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        intermediate_size=cfg["intermediate_size"],
        num_hidden_layers=cfg["num_hidden_layers"],
        num_attention_heads=cfg["num_attention_heads"],
        num_key_value_heads=cfg.get("num_key_value_heads",
                                    cfg["num_attention_heads"]),
        max_position_embeddings=cfg.get("max_position_embeddings", 4096),
        rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
        rope_theta=cfg.get("rope_theta", 10000.0),
        tie_word_embeddings=cfg.get("tie_word_embeddings", False),
        sliding_window=cfg.get("sliding_window") or 0,
        attention_bias=cfg.get("attention_bias",
                               cfg.get("model_type") == "qwen2"),
        dtype=dtype, remat=False)


def _mixtral_config_from_hf(cfg: dict, dtype: str) -> MixtralConfig:
    base = _llama_config_from_hf(cfg, dtype)
    from dataclasses import asdict
    return MixtralConfig(
        **asdict(base),
        num_local_experts=cfg.get("num_local_experts", 8),
        num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
        router_aux_loss_coef=cfg.get("router_aux_loss_coef", 0.02))


def _set(tree: dict, path: Tuple[str, ...], value):
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


def _attn_param(arr, key, H, Dh):
    """q/k/v/o torch weights → DenseGeneral kernels/biases."""
    if key == "o_proj.weight":          # [D, H*Dh] → [H*Dh, D]
        return ("o_proj", "kernel"), np.ascontiguousarray(arr.T)
    proj, kind = key.split(".")         # {q,k,v}_proj, weight|bias
    if kind == "bias":                  # [H*Dh] → [H, Dh]
        return (proj, "bias"), arr.reshape(H, Dh)
    D = arr.shape[1]                    # weight [H*Dh, D] → [D, H, Dh]
    return (proj, "kernel"), np.ascontiguousarray(arr.T).reshape(D, H, Dh)


def _ingest_llama(model_cfg: LlamaConfig,
                  params_iter: Iterable[Tuple[str, np.ndarray]]) -> dict:
    H, Hkv, Dh = (model_cfg.num_attention_heads,
                  model_cfg.num_key_value_heads, model_cfg.head_dim)
    tree: Dict = {}
    for name, arr in params_iter:
        if name.endswith(_SKIP_SUFFIXES):
            continue
        if name == "lm_head.weight":
            if not model_cfg.tie_word_embeddings:
                _set(tree, ("lm_head", "kernel"),
                     np.ascontiguousarray(arr.T))
            continue
        name = name.removeprefix("model.")
        if name == "embed_tokens.weight":
            _set(tree, ("embed_tokens", "embedding"), arr)
        elif name == "norm.weight":
            _set(tree, ("norm", "weight"), arr)
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            layer = f"layers_{idx}"
            if rest.startswith("self_attn."):
                key = rest.removeprefix("self_attn.")
                heads = H if key.startswith(("q_", "o_")) else Hkv
                sub, value = _attn_param(arr, key, heads, Dh)
                _set(tree, (layer, "self_attn") + sub, value)
            elif rest.startswith("mlp."):
                proj = rest.split(".")[1]   # gate_proj|up_proj|down_proj
                _set(tree, (layer, "mlp", proj, "kernel"),
                     np.ascontiguousarray(arr.T))
            elif rest in ("input_layernorm.weight",
                          "post_attention_layernorm.weight"):
                _set(tree, (layer, rest.split(".")[0], "weight"), arr)
            else:
                logger.warning(f"HF llama ingest: skipping {name}")
        else:
            logger.warning(f"HF llama ingest: skipping {name}")
    return tree


def _ingest_mixtral(model_cfg: MixtralConfig,
                    params_iter: Iterable[Tuple[str, np.ndarray]]) -> dict:
    """Llama mapping + block_sparse_moe → stacked-expert ``moe`` params."""
    E, D, I = (model_cfg.num_local_experts, model_cfg.hidden_size,
               model_cfg.intermediate_size)
    passthrough = []
    stacks: Dict[Tuple[str, str], np.ndarray] = {}

    def route():
        for name, arr in params_iter:
            if ".block_sparse_moe." not in name:
                passthrough.append((name, arr))
                continue
            prefix, rest = name.split(".block_sparse_moe.", 1)
            layer = f"layers_{prefix.split('.')[-1]}"
            if rest == "gate.weight":    # [E, D] → [D, E]
                yield layer, ("gate",), np.ascontiguousarray(arr.T)
            else:                        # experts.{e}.w{1,2,3}.weight
                _, e, w, _ = rest.split(".")
                shape = (E, I, D) if w == "w2" else (E, D, I)
                stack = stacks.setdefault((layer, w),
                                          np.empty(shape, dtype=arr.dtype))
                stack[int(e)] = arr.T
                continue

    tree: Dict = {}
    for layer, sub, value in route():
        _set(tree, (layer, "moe", ) + sub + ("kernel", ), value)
    for (layer, w), stack in stacks.items():
        _set(tree, (layer, "moe", w), stack)
    llama_tree = _ingest_llama(model_cfg, passthrough)
    for layer, sub in llama_tree.items():
        node = tree.setdefault(layer, {})
        node.update(sub)
    return tree


def build_model_and_params(checkpoint_engine, dtype: str = "bfloat16"):
    """(model, params) from a checkpoint engine with a ``model_config`` dict
    (HF ``config.json``).  Reference analog: ``engine_factory.build_hf_engine``
    dispatching on ``model_type`` (``engine_factory.py:69``)."""
    hf_cfg = checkpoint_engine.model_config
    model_type = hf_cfg.get("model_type", "llama")
    if model_type not in SUPPORTED_MODEL_TYPES:
        raise ValueError(
            f"unsupported model_type {model_type!r} "
            f"(supported: {SUPPORTED_MODEL_TYPES})")
    if model_type == "mixtral":
        cfg = _mixtral_config_from_hf(hf_cfg, dtype)
        params = _ingest_mixtral(cfg, checkpoint_engine.parameters())
        model = MixtralModel(cfg)
    else:
        cfg = _llama_config_from_hf(hf_cfg, dtype)
        params = _ingest_llama(cfg, checkpoint_engine.parameters())
        model = LlamaModel(cfg)
    if cfg.sliding_window:
        logger.info(f"{model_type}: sliding_window={cfg.sliding_window} "
                    "(enforced in the ragged attention path)")
    return model, params
