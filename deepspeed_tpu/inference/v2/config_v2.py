"""Inference v2 config (reference ``inference/v2/config_v2.py``:
``RaggedInferenceEngineConfig``, ``DeepSpeedTPConfig``,
``DSStateManagerConfig`` — same key names, TPU-sized defaults)."""

from typing import Optional

from ...runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    tp_size: int = 1


class DSStateManagerConfig(DeepSpeedConfigModel):
    max_tracked_sequences: int = 2048
    max_ragged_batch_size: int = 768          # token budget per engine step
    max_ragged_sequence_count: int = 512      # seqs per step
    max_context: int = 8192
    memory_config: Optional[dict] = None
    offload: bool = False

    # blocked-KV geometry (reference AllocationMode/KVCacheConfig)
    block_size: int = 128
    num_blocks: Optional[int] = None          # None → derived
    # Atom-tiled prefill (reference atom_builder analog): prefill runs are
    # laid out atom-aligned past a decode-only region so the Pallas paged
    # kernel can process `prefill_atom_size` same-sequence query rows per
    # tile.  0 → single-region per-token layout.
    prefill_atom_size: int = 16


class RaggedInferenceEngineConfig(DeepSpeedConfigModel):
    tensor_parallel: DeepSpeedTPConfig = DeepSpeedTPConfig()
    state_manager: DSStateManagerConfig = DSStateManagerConfig()
    dtype: str = "bfloat16"
    quantization_mode: Optional[str] = None
    # Quantized paged-KV serving (``kv_codec.py``): store the blocked KV
    # cache as int8/fp8 rows + per-token f32 scales (dequant-on-read ragged
    # forward) so one chip holds ~2-4× more concurrent sequences.  None
    # (default) keeps the full-precision cache — bit-identical programs.
    kv_cache_dtype: Optional[str] = None
    # Max greedy decode steps fused into one device program when every
    # running sequence is in pure decode (``ragged_forward.decode_burst``) —
    # one host round-trip per ``decode_burst`` tokens instead of per token.
    # 0/1 disables (exact per-step reference loop).
    decode_burst: int = 16
    # Opt-in: fuse SAMPLED decode too (device-side temperature/top-k/top-p
    # categorical with the jax PRNG).  Off by default because the draws are
    # a different (seed-deterministic) stream than the host loop's numpy
    # Generator; requires ``rng`` passed as a seed, not a Generator.
    decode_burst_sampling: bool = False
