"""InferenceEngineV2 — FastGen-style continuous batching (reference
``inference/v2/engine_v2.py:30``: ``put``/``query``/``flush`` scheduling API
over a ragged batch + blocked KV cache).

Each engine iteration packs a **fixed token budget** with a mix of decode
tokens (one per running sequence) and prefill chunks, runs ONE jitted ragged
step (``ragged_forward.py``), and samples next tokens for every sequence
whose pending tokens were fully consumed.  Prefills longer than the budget
stream across iterations automatically (chunked prefill).

Differences from the reference, by TPU design:
  * scheduling quantum = token budget (static shapes for XLA), not CUDA-graph
    atoms;
  * the engine is synchronous per step (``schedule_step``); serving loops
    (MII analog) call it in a thread.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ...utils.logging import logger
from .config_v2 import RaggedInferenceEngineConfig
from .kv_codec import resolve_kv_dtype
from .ragged import BlockedKVCache, DSStateManager, KVCacheExhausted
from .ragged_forward import RAGGED_FORWARDS


class InferenceEngineV2:

    def __init__(self, model, params=None, config=None):
        if isinstance(model, tuple):
            model, params = model
        if config is None:
            config = RaggedInferenceEngineConfig()
        elif isinstance(config, dict):
            config = RaggedInferenceEngineConfig(**config)
        self._config = config
        self.module = model
        cfg = model.config
        self.model_config = cfg
        name = type(model).__name__
        if name not in RAGGED_FORWARDS:
            raise ValueError(
                f"no ragged forward registered for {name} "
                f"(have: {list(RAGGED_FORWARDS)})")
        self._step_fn = RAGGED_FORWARDS[name]
        if params is None:
            raise ValueError("InferenceEngineV2 needs params")
        self.params = jax.tree_util.tree_map(jnp.asarray, params)

        # ---- tensor parallelism (reference inference_transformer_base
        # sharding + config tensor_parallel.tp_size): params shard via the
        # AutoTP rules, the KV cache shards over kv heads, and GSPMD
        # partitions the jitted step.  The Pallas kernels are single-device
        # programs, so tp>1 routes attention through the partitionable XLA
        # path (per-kv-head parallel).
        tp = int(getattr(config.tensor_parallel, "tp_size", 1) or 1)
        self._tp = tp
        self._tp_mesh = None
        # quantized paged-KV mode (kv_codec.py): the cache stores int8/fp8
        # rows + per-token f32 scales; the ragged step dequantizes on read.
        # Unset (None) keeps today's fp cache and exactly today's programs.
        self._kv_dtype = resolve_kv_dtype(
            getattr(config, "kv_cache_dtype", None))
        # weight-only quantized serving (reference quantization_mode):
        # resident weights in int8/int4 wire format, dequantized INSIDE the
        # jitted ragged step (and inside decode bursts — the wrapper is
        # traced by the burst program)
        from ..quant_serving import resolve_mode
        self._quant_bits = resolve_mode(
            getattr(config, "quantization_mode", None))
        self._quant_meta = {}
        if self._quant_bits is not None and tp > 1:
            raise NotImplementedError(
                "quantization_mode does not compose with tensor "
                "parallelism yet (quant grouping is laid out pre-shard)")
        if self._quant_bits is not None:
            from ..quant_serving import quantize_tree
            self.params, self._quant_meta = quantize_tree(
                self.params, self._quant_bits)
            base_step = self._step_fn
            meta, dt = self._quant_meta, jnp.dtype(config.dtype)

            def dq_step(params, *a, **kw):
                from ..quant_serving import dequantize_tree
                return base_step(dequantize_tree(params, meta, dt), *a,
                                 **kw)

            # jit the wrapper with the SAME statics AND the kv-cache
            # donation as the registered step (the inner jit's donation is
            # ignored once inlined — dropping it would double peak KV HBM);
            # decode_burst traces the wrapper inside its own program
            self._step_fn = jax.jit(
                dq_step, static_argnames=("cfg", "block_size", "layout",
                                          "use_kernel", "kv_dtype"),
                donate_argnums=(1, ))
        if tp > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
            devs = jax.devices()
            n_kv = cfg.num_key_value_heads
            n_q = getattr(cfg, "num_attention_heads", n_kv)
            # GQA with fewer kv heads than tp ranks: REPLICATE kv (cache +
            # k/v projections — the reference's kernel injection replicates
            # kv heads the same way for tp > n_kv); q/o still shard.
            kv_replicated = (n_kv % tp != 0 and tp % n_kv == 0
                             and n_q % tp == 0)
            if len(devs) % tp or (n_kv % tp and not kv_replicated):
                raise ValueError(
                    f"tp_size={tp} must divide the device count "
                    f"({len(devs)}) and either num_key_value_heads "
                    f"({n_kv}) or — for replicated-kv GQA — be a "
                    f"multiple of it with num_attention_heads ({n_q}) "
                    "divisible by tp")
            self._tp_mesh = Mesh(np.array(devs[:tp]), ("tp", ))
            from ...module_inject import shard_params_for_tp
            rules = None
            import sys as _sys
            mod = _sys.modules.get(type(model).__module__)
            if hasattr(mod, "tp_rules"):
                # shard_params_for_tp restricts specs to the mesh's axes
                # (drops 'zero'/'ep' etc. training pseudo-axes)
                rules = mod.tp_rules(cfg)
            if kv_replicated and rules is not None:
                # replication is the INTENDED layout here — override the
                # k/v rules explicitly rather than riding the divisibility
                # fallback (which warns per layer as if misconfigured)
                rules = dict(rules)
                for key in list(rules):
                    if "k_proj" in key or "v_proj" in key:
                        rules[key] = P()
            self.params = shard_params_for_tp(self.params, self._tp_mesh,
                                              rules=rules)
            # kv cache: shard over kv heads when they divide tp, else the
            # replicated-kv GQA mode (k/v proj leaves auto-replicate in
            # shard_params_for_tp via the divisibility fallback)
            self._kv_sharding = NamedSharding(
                self._tp_mesh,
                P() if kv_replicated
                else P(None, None, None, None, "tp", None))
            # quantized KV × tp (ROADMAP serving follow-on (b)): the
            # per-(layer, k/v, token, head) f32 scales shard WITH the
            # cache — their trailing dim IS the kv-head dim the cache
            # shards on, so each rank holds exactly the scales of its own
            # cache shard and the on-read dequant stays rank-local
            self._kv_scales_sharding = NamedSharding(
                self._tp_mesh,
                P() if kv_replicated else P(None, None, None, None, "tp"))
        else:
            self._kv_sharding = None
            self._kv_scales_sharding = None

        sm = config.state_manager
        block_size = sm.block_size
        max_blocks_per_seq = -(-sm.max_context // block_size)
        num_blocks = sm.num_blocks
        if num_blocks is None:
            # enough for half the tracked sequences at full context (+1
            # garbage block) — the reference sizes from free memory
            num_blocks = 1 + max(sm.max_ragged_sequence_count,
                                 (sm.max_tracked_sequences *
                                  max_blocks_per_seq) // 2)
        self.kv_cache = BlockedKVCache(
            cfg.num_hidden_layers, num_blocks, block_size,
            cfg.num_key_value_heads, cfg.head_dim,
            dtype=jnp.dtype(config.dtype), kv_dtype=self._kv_dtype)
        self.state_manager = DSStateManager(sm, self.kv_cache)
        self._budget = int(sm.max_ragged_batch_size)
        # the device-side cache the step functions thread: a plain array
        # (fp path) or the (data, scales) pytree (quantized path)
        self._kv = self.kv_cache.data if self._kv_dtype is None \
            else (self.kv_cache.data, self.kv_cache.scales)
        if self._kv_sharding is not None:
            if self._kv_dtype is None:
                self._kv = jax.device_put(self._kv, self._kv_sharding)
                # drop the replicated original — a full unsharded cache
                # pinned to device 0 would defeat the point of sharding it
                self.kv_cache.data = self._kv
            else:
                self._kv = jax.device_put(
                    self._kv,
                    (self._kv_sharding, self._kv_scales_sharding))
                self.kv_cache.data, self.kv_cache.scales = self._kv
        logger.info(
            f"InferenceEngineV2: budget={self._budget} blocks={num_blocks}"
            f"×{block_size} max_seqs={self.state_manager.max_seqs}")

    # ------------------------------------------------------------- put/query
    def put(self, batch_uids, batch_tokens, do_schedule=False):
        """Queue prompt (or continuation) tokens (reference ``put`` :130 also
        runs the engine; here scheduling is explicit — pass
        ``do_schedule=True`` for reference-style behavior).

        An unknown uid starts a NEW sequence (the admission path).  A uid
        whose sequence already finished raises instead of silently
        resurrecting it: the done sequence's KV prefix and token history
        would leak into what the caller thinks is a fresh request — flush
        first (a flushed uid is unknown again and admits cleanly).  The
        check runs over the whole batch BEFORE any sequence mutates, so a
        rejected put leaves every sequence untouched (a retry after the
        flush must not double-extend the earlier uids)."""
        batch_uids = list(batch_uids)
        for uid in batch_uids:
            seq = self.state_manager.get_sequence(uid)
            if seq is not None and seq.done:
                raise ValueError(
                    f"put() on finished uid {uid!r} — flush it first "
                    "(continuing a done sequence would silently reuse its "
                    "KV prefix and token history)")
        for uid, toks in zip(batch_uids, batch_tokens):
            toks = [int(t) for t in np.asarray(toks).reshape(-1)]
            seq = self.state_manager.get_or_create_sequence(uid)
            seq.tokens.extend(toks)
        if do_schedule:
            return self.schedule_step()
        return {}

    def query(self, uid):
        """Latest state of a sequence (reference ``query``): returns
        (generated_token_count, last_token) once past the prompt."""
        seq = self.state_manager.get_sequence(uid)
        if seq is None:
            return None
        return {"uid": uid, "length": seq.cur_length,
                "seen": seq.seen_tokens, "done": seq.done,
                "tokens": list(seq.tokens)}

    def flush(self, uids):
        """Release sequences (reference ``flush`` :188)."""
        for uid in uids:
            self.state_manager.flush_sequence(uid)

    # -------------------------------------------------------------- schedule
    def _atom_layout(self):
        """Static (decode_cap, atom) region split used on prefill-heavy
        steps: [0, decode_cap) single decode tokens (per-token paged
        kernel), [decode_cap, T) prefill runs aligned to ``atom`` tiles
        (atom-tiled kernel — the reference atom_builder analog).  Only two
        layouts ever compile: this one and the flat (0, 0) legacy."""
        sm = self._config.state_manager
        atom = sm.prefill_atom_size
        if not atom:
            return (0, 0)
        decode_cap = min(sm.max_ragged_sequence_count, self._budget // 2)
        if self._budget - decode_cap < atom:
            return (0, 0)  # no room for a prefill region
        # the prefill region must be a whole number of atom tiles — grow
        # the decode region to absorb the remainder
        decode_cap = self._budget - (self._budget - decode_cap) // atom * atom
        return (decode_cap, atom)

    def _pick_layout(self):
        """Per-step layout choice: atom regions only when prefill dominates
        (a decode-heavy step keeps the flat layout — zero regression)."""
        decode_cap, atom = self._atom_layout()
        if not atom:
            return (0, 0)
        n_decode = n_prefill = 0
        for seq in self.state_manager.tracked_sequences.values():
            if seq.done:
                continue
            # O(1) pending count — pending() slices the full token list
            p = len(seq.tokens) - seq.seen_tokens
            if p == 1:
                n_decode += 1
            elif p > 1:
                n_prefill += p
        if n_prefill >= max(atom, n_decode):
            return (decode_cap, atom)
        return (0, 0)

    def _build_batch(self):
        """Pack the token budget: decode tokens first (latency), then
        prefill chunks (throughput) — the reference scheduler's policy.
        With an atom layout, decode tokens fill the decode region and
        prefill runs are atom-aligned in the prefill region."""
        T = self._budget
        sm = self.state_manager
        decode_cap, atom = layout = self._pick_layout()
        toks = np.zeros(T, np.int32)
        pos = np.zeros(T, np.int32)
        slots = np.zeros(T, np.int32)  # slot 0 → garbage block
        finishing = []  # (seq, buffer index of its last scheduled token)
        placed = 0
        deferred = 0        # sequences the KV pool could not grow this step
        deferred_want = 0   # blocks those sequences needed and couldn't get

        d_cur = 0                      # decode-region cursor
        p_cur = decode_cap             # prefill-region cursor (atom-aligned)
        order = sorted(sm.tracked_sequences.values(),
                       key=lambda s: len(s.pending()))
        for seq in order:
            if seq.done:
                continue
            pending = seq.pending()
            if not pending:
                continue
            if atom:
                if len(pending) == 1 and d_cur < decode_cap:
                    start, room = d_cur, 1
                else:
                    start = p_cur
                    room = T - p_cur
                    if room <= 0 and d_cur < decode_cap:
                        # prefill region exhausted but decode rows are free:
                        # advance this sequence by ONE token through a spare
                        # decode row.  Exact: the decode path masks keys by
                        # position, and every earlier token of the sequence
                        # is already in cache (round-2 advisor finding —
                        # schedulable work was left on the table)
                        start, room = d_cur, 1
                if room <= 0:
                    continue
            else:
                start = d_cur
                room = T - d_cur
                if room <= 0:
                    break
            take = min(len(pending), room)
            # KV-pool pressure: schedule only what the free blocks can hold
            # (the reference scheduler's deferral; a dry pool must not crash
            # the step — blocks free as other sequences flush)
            take = min(take, sm.schedulable_tokens(
                seq, seq.seen_tokens + take))
            if take <= 0:
                deferred += 1
                # blocks this sequence would need to advance ONE token —
                # the wanted_blocks figure a typed exhaustion reports
                deferred_want += max(
                    1, self.kv_cache.blocks_for(seq.seen_tokens + 1)
                    - len(seq.blocks))
                continue
            sm.ensure_capacity(seq, seq.seen_tokens + take)
            toks[start:start + take] = pending[:take]
            pos[start:start + take] = np.arange(
                seq.seen_tokens, seq.seen_tokens + take)
            slots[start:start + take] = seq.slot
            if take == len(pending):
                finishing.append((seq, start + take - 1))
            seq.seen_tokens += take
            placed += take
            if atom:
                if start < decode_cap:   # landed in the decode region
                    d_cur += 1
                else:
                    # advance to the next atom boundary (intra-atom pads)
                    p_cur = start + (-(-take // atom)) * atom
            else:
                d_cur += take
        if placed == 0:
            if deferred:
                # nothing schedulable AND nothing in flight to free blocks:
                # deferring forever would spin — surface the exhaustion as
                # the typed capacity error so a serving scheduler can
                # catch-and-preempt (serving/scheduler.py)
                raise KVCacheExhausted(
                    deferred_want, sm.free_blocks,
                    detail=f"{deferred} sequence(s) deferred with 0 "
                    f"schedulable tokens and no other work in flight — "
                    f"raise state_manager.num_blocks, lower concurrency, "
                    f"preempt, or flush finished sequences")
            return None
        last_idx = np.zeros(sm.max_seqs, dtype=np.int32)
        for seq, idx in finishing:
            last_idx[seq.slot] = idx
        return toks, pos, slots, last_idx, finishing, layout

    @staticmethod
    def _sample_row(row, temperature, top_k, top_p, rng):
        """Host-side categorical sampling with the reference generate
        options (temperature / top-k / nucleus top-p)."""
        logits = row.astype(np.float64) / max(temperature, 1e-6)
        if top_k:
            kth = np.partition(logits, -int(top_k))[-int(top_k)]
            logits = np.where(logits < kth, -np.inf, logits)
        p = np.exp(logits - logits.max())
        p /= p.sum()
        if top_p and top_p < 1.0:
            order = np.argsort(-p)
            csum = np.cumsum(p[order])
            # smallest prefix whose mass reaches top_p (always ≥ 1 token)
            keep = csum - p[order] < top_p
            mask = np.zeros_like(p, dtype=bool)
            mask[order[keep]] = True
            p = np.where(mask, p, 0.0)
            p /= p.sum()
        return int(rng.choice(len(p), p=p))

    def schedule_step(self, do_sample=False, temperature=1.0, rng=None,
                      top_k=0, top_p=1.0):
        """One ragged iteration.  Returns {uid: sampled_next_token} for every
        sequence whose pending tokens were fully consumed this step.

        ``rng`` may be a ``np.random.Generator`` or a seed; either way the
        Generator is created once and advances across tokens and steps (a
        seed re-seeded per token would sample identical draws every time).
        """
        if do_sample:
            if isinstance(rng, np.random.Generator):
                self._rng = rng
                self._rng_seed = None
            elif (getattr(self, "_rng", None) is None
                  or (rng is not None and rng != getattr(self, "_rng_seed", None))):
                # create once per distinct seed; advances across tokens/steps
                self._rng = np.random.default_rng(rng)
                self._rng_seed = rng
        batch = self._build_batch()
        if batch is None:
            return {}
        toks, pos, slots, last_idx, finishing, layout = batch
        step_args = (self.params, self._kv, jnp.asarray(toks),
                     jnp.asarray(pos), jnp.asarray(slots),
                     jnp.asarray(self.state_manager.block_table),
                     jnp.asarray(last_idx))
        step_kw = dict(cfg=self.model_config,
                       block_size=self.kv_cache.block_size, layout=layout,
                       use_kernel=self._tp == 1, kv_dtype=self._kv_dtype)
        from ...profiling import cost_model
        if cost_model.capturing():
            # compiled-cost capture of the serving prefill/decode program
            # (one analysis compile per distinct layout, only while
            # capture is armed — docs/observability.md "MFU & HBM");
            # layout (0,0) is the flat/decode-heavy program, (d,a) the
            # atom-tiled prefill one
            cost_model.capture_jit_call(
                f"serve/ragged_step[{layout[0]}x{layout[1]}]",
                self._step_fn, step_args, step_kw,
                meta={"layout": list(layout)})
        logits, self._kv = self._step_fn(*step_args, **step_kw)
        out = {}
        if finishing:
            if do_sample:
                # fetch ONLY the finishing rows ([F, V]), not every slot
                slots_f = jnp.asarray([seq.slot for seq, _ in finishing])
                lg = np.asarray(logits[slots_f])
                for i, (seq, _) in enumerate(finishing):
                    out[seq.uid] = self._sample_row(
                        lg[i], temperature, top_k, top_p, self._rng)
            else:
                # greedy: argmax on device, fetch one int per slot instead
                # of [max_seqs, V] logits (the per-step device→host tax on
                # a decode loop)
                toks = np.asarray(jnp.argmax(logits, axis=-1))
                for seq, _ in finishing:
                    out[seq.uid] = int(toks[seq.slot])
        return out

    # ---------------------------------------------------------- decode burst
    def _decode_burst_step(self, active_uids, produced, max_new_tokens,
                           cap, sample=False, temperature=1.0, top_k=0,
                           top_p=1.0, seed=None):
        """Run up to ``cap`` greedy decode iterations on device in one
        program (``ragged_forward.decode_burst``).  Eligible only when
        EVERY active sequence has exactly one pending token (pure decode —
        a pending prefill chunk keeps the per-step scheduler).  Returns
        {uid: [k tokens]} or None if not eligible."""
        sm = self.state_manager
        seqs = []
        for uid in active_uids:
            seq = sm.get_sequence(uid)
            if len(seq.tokens) - seq.seen_tokens != 1:
                return None
            seqs.append(seq)
        if not seqs:
            return None
        k = min(cap, min(max_new_tokens - len(produced[s.uid])
                         for s in seqs))
        if k < 2:
            return None
        return self._run_burst(seqs, k, sample, temperature, top_k, top_p,
                               seed)

    def burst_decode(self, uids=None, max_tokens=16, do_sample=False,
                     temperature=1.0, top_k=0, top_p=1.0, rng=None):
        """Public fused-decode entry for reference-style serving loops
        (``put``/``schedule_step`` callers): run up to ``max_tokens`` decode
        iterations on device in one program for the given sequences and
        return ``{uid: [tokens]}``.  Requires every targeted sequence to be
        in pure decode (exactly one pending token) — raises otherwise, so a
        scheduler can fall back to ``schedule_step``.  Sampling uses the
        device PRNG path (seed-deterministic; pass ``rng`` as a seed)."""
        sm = self.state_manager
        if uids is None:
            uids = [s.uid for s in sm.tracked_sequences.values()
                    if not s.done]
        seqs = []
        for uid in uids:
            seq = sm.get_sequence(uid)
            if seq is None or seq.done:
                raise ValueError(f"uid {uid!r} is not an active sequence")
            if len(seq.tokens) - seq.seen_tokens != 1:
                raise ValueError(
                    f"uid {uid!r} is not in pure decode "
                    f"({len(seq.pending())} pending tokens) — run "
                    "schedule_step until prefill drains")
            seqs.append(seq)
        k = int(max_tokens)
        cap = int(self._config.decode_burst or 0)
        if cap > 1:   # an explicit call may exceed a DISABLED config, not
            k = min(k, cap)   # a configured cap
        if not seqs or k < 2:
            return {}
        if do_sample and isinstance(rng, np.random.Generator):
            raise ValueError("burst_decode sampling needs a seed, not a "
                             "numpy Generator (device PRNG stream)")
        # None = the KV pool can't afford a burst right now → empty result;
        # the caller's schedule_step path defers until blocks free
        return self._run_burst(seqs, k, do_sample, temperature,
                               top_k, top_p, rng) or {}

    def _run_burst(self, seqs, k, sample, temperature, top_k, top_p, seed):
        sm = self.state_manager
        # KV-pool pressure: a burst pre-allocates k positions per sequence
        # from the SHARED free pool — shrink k until the total new-block
        # demand fits, falling back to the per-step scheduler (which
        # defers) below 2

        def _new_blocks(kk):
            return sum(
                max(0, sm.kv_cache.blocks_for(s.seen_tokens + kk)
                    - len(s.blocks)) for s in seqs)

        while k >= 2 and _new_blocks(k) > sm.free_blocks:
            k //= 2
        if k < 2:
            return None
        # quantize to the floor power of two: each distinct static k is its
        # own compiled program, so arbitrary k values would compile per
        # remaining-token count — pow2 bounds the variants to log2(cap)
        k = 1 << (k.bit_length() - 1)
        n = sm.max_seqs
        tok0 = np.zeros(n, np.int32)
        pos0 = np.zeros(n, np.int32)
        act = np.zeros(n, bool)
        for seq in seqs:
            sm.ensure_capacity(seq, seq.seen_tokens + k)
            tok0[seq.slot] = seq.tokens[seq.seen_tokens]
            pos0[seq.slot] = seq.seen_tokens
            act[seq.slot] = True
        from .ragged_forward import decode_burst
        if sample:
            if getattr(self, "_burst_key", None) is None or \
                    seed != getattr(self, "_burst_seed", None):
                self._burst_key = jax.random.PRNGKey(seed or 0)
                self._burst_seed = seed
            self._burst_key, key = jax.random.split(self._burst_key)
        else:
            key = None
        burst_args = (self.params, self._kv, jnp.asarray(tok0),
                      jnp.asarray(pos0), jnp.asarray(act),
                      jnp.asarray(sm.block_table))
        burst_kw = dict(step_fn=self._step_fn, cfg=self.model_config,
                        block_size=self.kv_cache.block_size, k=k,
                        use_kernel=self._tp == 1, sample=sample, key=key,
                        temperature=float(temperature), top_k=int(top_k),
                        top_p=float(top_p), kv_dtype=self._kv_dtype)
        from ...profiling import cost_model
        if cost_model.capturing():
            # k is static (pow2-quantized above), so the burst variants are
            # a bounded program family worth tabulating per k
            cost_model.capture_jit_call(
                f"serve/decode_burst[k={k}]", decode_burst, burst_args,
                burst_kw, meta={"k": int(k)})
        toks_out, self._kv = decode_burst(*burst_args, **burst_kw)
        toks_out = np.asarray(toks_out)      # ONE fetch for k×seqs tokens
        self.burst_steps = getattr(self, "burst_steps", 0) + 1
        out = {}
        for seq in seqs:
            # k tokens scheduled on device: t0 (the pending one) + the k-1
            # fed-back generations; invariant len(tokens) == seen + 1 holds
            # with the newest generation left pending for the next round
            seq.seen_tokens += k
            col = toks_out[:, seq.slot]
            seq.tokens.extend(int(t) for t in col)
            out[seq.uid] = [int(t) for t in col]
        return out

    # ------------------------------------------------------------- generate
    def _mark_done(self, uid, produced, tok, eos_token_id, max_new_tokens):
        """Record one generated token and apply the completion rule (EOS or
        the max-new-tokens budget) — the ONE place both the per-step loop
        and the burst path decide a sequence is finished.  Returns True when
        the sequence just completed (the caller drops it from its active
        set); overshoot past EOS inside a burst window is garbage the flush
        drops — ``produced`` truncates exactly."""
        produced[uid].append(tok)
        if (eos_token_id is not None and tok == eos_token_id) or \
                len(produced[uid]) >= max_new_tokens:
            self.state_manager.get_sequence(uid).done = True
            return True
        return False

    def generate(self, prompts, max_new_tokens=32, eos_token_id=None,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 rng=None):
        """Convenience continuous-batching loop: all prompts in flight at
        once, chunked prefill + interleaved decode."""
        uids = list(range(len(prompts)))
        self.put(uids, prompts)
        produced = {u: [] for u in uids}
        active = set(uids)
        burst_cap = int(self._config.decode_burst or 0)
        burst_sample = False
        if do_sample:
            # fused sampling is opt-in AND needs a seed (not a Generator —
            # the device stream can't replicate numpy's)
            if (self._config.decode_burst_sampling
                    and not isinstance(rng, np.random.Generator)):
                burst_sample = True
            else:
                burst_cap = 0
        while active:
            if burst_cap > 1:
                burst = self._decode_burst_step(
                    active, produced, max_new_tokens, burst_cap,
                    sample=burst_sample, temperature=temperature,
                    top_k=top_k, top_p=top_p, seed=rng)
                if burst is not None:
                    for uid, toks in burst.items():
                        for tok in toks:
                            if self._mark_done(uid, produced, tok,
                                               eos_token_id,
                                               max_new_tokens):
                                active.discard(uid)
                                break
                    continue
            next_tokens = self.schedule_step(do_sample=do_sample,
                                             temperature=temperature,
                                             top_k=top_k, top_p=top_p,
                                             rng=rng)
            if not next_tokens:
                # a chunked prefill step consumes budget without finishing
                # any sequence — keep going while work remains
                if any(self.state_manager.get_sequence(u).pending()
                       for u in active):
                    continue
                break
            for uid, tok in next_tokens.items():
                if self._mark_done(uid, produced, tok, eos_token_id,
                                   max_new_tokens):
                    active.discard(uid)
                else:
                    # decode continues next step
                    self.state_manager.get_sequence(uid).tokens.append(tok)
        self.flush(uids)
        return [produced[u] for u in uids]
