"""Quantized paged-KV codecs — ``kv_cache_dtype: int8 | fp8`` serving mode.

Built on the :mod:`deepspeed_tpu.comm.collectives.quantized` codec family
(the ZeRO++ lineage, arxiv 2306.10209): the paged KV cache stores values in
a narrow wire format plus one f32 scale per written token row, so one chip
holds ~2-4× more concurrent sequences than a bf16/f32 cache.  Quantization
happens once, on the cache-scatter write; the ragged forward dequantizes
**on read** — only the gathered attention context is ever widened, never
the whole cache.

Scale granularity is per (layer, k/v, token, head): one scale over a
token's ``[Dh]`` head row — 4·Hkv bytes/token/layer of overhead (well
under 2% for Dh ≥ 64), fine enough that int8 greedy decode stays
token-identical to the fp cache (the serve_bench ``--smoke`` gate pins
this over ≥64 decode steps).

TPU note: the quantized path reads through the XLA gather fallback of
``ragged_forward._paged_attention`` — the Pallas paged kernel streams fp
pages and does not (yet) consume scales, so ``use_kernel`` is forced off
when a codec is active.
"""

import jax.numpy as jnp

from ...comm.collectives.quantized import (ROWWISE_FORMATS, rowwise_codec,
                                           rowwise_storage_dtype)

#: accepted ``kv_cache_dtype`` spellings → canonical wire format
KV_CACHE_DTYPES = {"int8": "int8", "q8": "int8",
                   "fp8": "fp8", "fp8_e4m3": "fp8", "e4m3": "fp8"}


def resolve_kv_dtype(name):
    """``kv_cache_dtype`` config value → canonical format name or None.

    Unknown formats raise loudly at engine build (a typo must not silently
    serve an fp cache while the operator budgets for a quantized one)."""
    if name is None:
        return None
    fmt = KV_CACHE_DTYPES.get(str(name).lower())
    if fmt is None:
        raise ValueError(
            f"kv_cache_dtype={name!r} is not a quantized-KV format "
            f"(have {sorted(set(KV_CACHE_DTYPES))}; unset = full-precision "
            "cache)")
    return fmt


def storage_dtype(fmt):
    """Canonical format → element dtype the cache array is allocated as."""
    return rowwise_storage_dtype(fmt)


def codec(fmt):
    """Canonical format → (encode, decode) over ``[..., Hkv, Dh]`` values
    with one scale per ``[Dh]`` head row (decode returns f32)."""
    assert fmt in ROWWISE_FORMATS, fmt
    return rowwise_codec(fmt, reduce_axes=1)


def kv_bytes_per_token(num_layers, num_kv_heads, head_dim, fmt=None,
                       fp_dtype=jnp.bfloat16):
    """Cache bytes one token occupies (both K and V, all layers) — the
    ``kv_bytes_per_token`` field of serve_bench's ``--json`` rows.
    ``fmt=None`` is the full-precision cache in ``fp_dtype``."""
    elems = 2 * num_layers * num_kv_heads * head_dim
    if fmt is None:
        return elems * jnp.dtype(fp_dtype).itemsize
    # int8 and fp8 both store 1 byte/element + one f32 scale per (layer,
    # k/v, token, head) row
    return (elems * jnp.dtype(storage_dtype(fmt)).itemsize
            + 2 * num_layers * num_kv_heads * 4)
