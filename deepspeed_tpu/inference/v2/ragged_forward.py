"""Ragged (paged-KV) model forward (reference
``inference/v2/model_implementations/llama_v2`` + the ragged kernel suite
``kernels/ragged_ops``: linear_blocked_kv_rotary, blocked flash, logits_gather).

One jitted function processes a *flat token buffer* ``[T]`` — the union of
prefill chunks and single decode tokens from many sequences — against the
paged KV cache.  The reference does this with hand-written CUDA (atom builder
+ blocked flash); here the batch metadata (positions, sequence slots, block
tables) turns the same computation into gathers/scatters XLA schedules, with
the attention core a candidate for a Pallas paged kernel (the math below is
already blocked: swap `_paged_attention` for a kernel without touching the
rest).

Token semantics: every token's K/V is written to the cache *before* attention
runs, and each token attends to cache positions ≤ its own — so a multi-token
prefill chunk is causal within itself and sees all earlier chunks, and a
decode token sees the whole prefix.  Exactly FastGen's ragged semantics.
"""

import functools

import jax
import jax.numpy as jnp

from ...models.llama import _rope_freqs


def _rotary(x, cos, sin, positions):
    """x: [T, H, Dh]; positions: [T]."""
    c = cos[positions][:, None, :]
    s = sin[positions][:, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(x.dtype)


def _rmsnorm(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * w).astype(x.dtype)


def _paged_attention(q, k_cache, v_cache, tables_t, positions, block_size):
    """q: [T, H, Dh]; caches: [num_blocks, bs, Hkv, Dh]; tables_t: [T, maxb];
    positions: [T].  Returns [T, H, Dh].

    On TPU: the Pallas paged kernel (block pages streamed through VMEM via
    scalar-prefetched table indices).  Fallback: XLA gather of each token's
    block run with position masking."""
    import os
    if jax.default_backend() == "tpu" and not os.environ.get(
            "DS_TPU_DISABLE_PALLAS_PAGED"):
        from ...ops.pallas.paged_attention import paged_attention
        return paged_attention(q, k_cache, v_cache, tables_t, positions)
    T, H, Dh = q.shape
    Hkv = k_cache.shape[2]
    maxb = tables_t.shape[1]
    ctx = maxb * block_size
    k_ctx = k_cache[tables_t].reshape(T, ctx, Hkv, Dh)
    v_ctx = v_cache[tables_t].reshape(T, ctx, Hkv, Dh)
    g = H // Hkv
    qg = q.reshape(T, Hkv, g, Dh).astype(jnp.float32)
    scores = jnp.einsum("tkgd,tckd->tkgc", qg,
                        k_ctx.astype(jnp.float32)) * (Dh**-0.5)
    pos_ctx = jnp.arange(ctx)[None, None, None, :]
    mask = pos_ctx <= positions[:, None, None, None]
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("tkgc,tckd->tkgd", probs, v_ctx.astype(jnp.float32))
    return out.reshape(T, H, Dh).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("cfg", "block_size"),
                   donate_argnums=(1, ))
def llama_ragged_step(params, kv_data, token_ids, positions, seq_slots,
                      block_tables, last_token_idx, *, cfg, block_size):
    """One ragged engine iteration for the Llama family.

    Args:
      params: LlamaModel param tree (``models/llama.py`` naming).
      kv_data: [L, 2, num_blocks, bs, Hkv, Dh] paged cache (donated).
      token_ids/positions/seq_slots: [T] flat batch (padding: slot 0 = the
        reserved garbage block row, position 0).
      block_tables: [max_seqs, maxb] int32.
      last_token_idx: [max_seqs] int32 — buffer index of each slot's last
        scheduled token (logits gather; 0 for idle slots).

    Returns (logits [max_seqs, V] fp32, new kv_data).
    """
    dtype = jnp.dtype(cfg.dtype)
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    eps = cfg.rms_norm_eps
    cos, sin = _rope_freqs(Dh, cfg.max_position_embeddings, cfg.rope_theta)
    cos = jnp.asarray(cos, jnp.float32)
    sin = jnp.asarray(sin, jnp.float32)

    x = params["embed_tokens"]["embedding"][token_ids].astype(dtype)  # [T, D]
    tables_t = block_tables[seq_slots]                       # [T, maxb]
    blk = tables_t[jnp.arange(token_ids.shape[0]),
                   positions // block_size]                  # [T]
    off = positions % block_size

    for l in range(cfg.num_hidden_layers):
        lp = params[f"layers_{l}"]
        attn, mlp = lp["self_attn"], lp["mlp"]
        h = _rmsnorm(x, lp["input_layernorm"]["weight"], eps)
        q = jnp.einsum("td,dhk->thk", h,
                       attn["q_proj"]["kernel"].astype(dtype))
        k = jnp.einsum("td,dhk->thk", h,
                       attn["k_proj"]["kernel"].astype(dtype))
        v = jnp.einsum("td,dhk->thk", h,
                       attn["v_proj"]["kernel"].astype(dtype))
        q = _rotary(q, cos, sin, positions)
        k = _rotary(k, cos, sin, positions)
        # scatter this batch's K/V into the paged cache (linear_blocked_kv_
        # rotary analog), then attend against the updated pages
        kv_data = kv_data.at[l, 0, blk, off].set(k.astype(kv_data.dtype))
        kv_data = kv_data.at[l, 1, blk, off].set(v.astype(kv_data.dtype))
        out = _paged_attention(q, kv_data[l, 0], kv_data[l, 1], tables_t,
                               positions, block_size)
        o = out.reshape(out.shape[0], H * Dh)
        x = x + jnp.einsum("tf,fd->td", o,
                           attn["o_proj"]["kernel"].astype(dtype))
        h2 = _rmsnorm(x, lp["post_attention_layernorm"]["weight"], eps)
        gate = h2 @ mlp["gate_proj"]["kernel"].astype(dtype)
        up = h2 @ mlp["up_proj"]["kernel"].astype(dtype)
        x = x + (jax.nn.silu(gate) * up) @ mlp["down_proj"]["kernel"].astype(
            dtype)

    x = _rmsnorm(x, params["norm"]["weight"], eps)
    # logits_gather analog: only each slot's last token reaches the LM head
    xl = x[last_token_idx].astype(jnp.float32)               # [max_seqs, D]
    if cfg.tie_word_embeddings:
        logits = xl @ params["embed_tokens"]["embedding"].T.astype(jnp.float32)
    else:
        logits = xl @ params["lm_head"]["kernel"].astype(jnp.float32)
    return logits, kv_data


RAGGED_FORWARDS = {"LlamaModel": llama_ragged_step}
