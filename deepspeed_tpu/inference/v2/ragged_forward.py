"""Ragged (paged-KV) model forward (reference
``inference/v2/model_implementations/llama_v2`` + the ragged kernel suite
``kernels/ragged_ops``: linear_blocked_kv_rotary, blocked flash, logits_gather).

One jitted function processes a *flat token buffer* ``[T]`` — the union of
prefill chunks and single decode tokens from many sequences — against the
paged KV cache.  The reference does this with hand-written CUDA (atom builder
+ blocked flash); here the batch metadata (positions, sequence slots, block
tables) turns the same computation into gathers/scatters XLA schedules, with
the attention core a candidate for a Pallas paged kernel (the math below is
already blocked: swap `_paged_attention` for a kernel without touching the
rest).

Token semantics: every token's K/V is written to the cache *before* attention
runs, and each token attends to cache positions ≤ its own — so a multi-token
prefill chunk is causal within itself and sees all earlier chunks, and a
decode token sees the whole prefix.  Exactly FastGen's ragged semantics.
"""

import functools

import jax
import jax.numpy as jnp

from ...models.llama import _rope_freqs


def _rotary(x, cos, sin, positions):
    """x: [T, H, Dh]; positions: [T]."""
    c = cos[positions][:, None, :]
    s = sin[positions][:, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(x.dtype)


def _rmsnorm(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * w).astype(x.dtype)


def _paged_attention(q, k_cache, v_cache, tables_t, positions, block_size,
                     window=0):
    """q: [T, H, Dh]; caches: [num_blocks, bs, Hkv, Dh]; tables_t: [T, maxb];
    positions: [T]; window: sliding-window size (0 → full causal).
    Returns [T, H, Dh].

    On TPU: the Pallas paged kernel (block pages streamed through VMEM via
    scalar-prefetched table indices).  Fallback: XLA gather of each token's
    block run with position masking."""
    import os
    if (window == 0 and jax.default_backend() == "tpu"
            and not os.environ.get("DS_TPU_DISABLE_PALLAS_PAGED")):
        from ...ops.pallas.paged_attention import paged_attention
        return paged_attention(q, k_cache, v_cache, tables_t, positions)
    T, H, Dh = q.shape
    Hkv = k_cache.shape[2]
    maxb = tables_t.shape[1]
    ctx = maxb * block_size
    k_ctx = k_cache[tables_t].reshape(T, ctx, Hkv, Dh)
    v_ctx = v_cache[tables_t].reshape(T, ctx, Hkv, Dh)
    g = H // Hkv
    qg = q.reshape(T, Hkv, g, Dh).astype(jnp.float32)
    scores = jnp.einsum("tkgd,tckd->tkgc", qg,
                        k_ctx.astype(jnp.float32)) * (Dh**-0.5)
    pos_ctx = jnp.arange(ctx)[None, None, None, :]
    pos_q = positions[:, None, None, None]
    mask = pos_ctx <= pos_q
    if window:
        mask &= pos_ctx > pos_q - window
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("tkgc,tckd->tkgd", probs, v_ctx.astype(jnp.float32))
    return out.reshape(T, H, Dh).astype(q.dtype)


def _qkv(h, proj, dtype):
    """DenseGeneral [T, D] → [T, H, Dh] with optional bias (Qwen2)."""
    y = jnp.einsum("td,dhk->thk", h, proj["kernel"].astype(dtype))
    if "bias" in proj:
        y = y + proj["bias"].astype(dtype)
    return y


def _ragged_attention_block(lp_attn, h, kv_layer, blk, off, tables_t,
                            positions, cos, sin, *, cfg, block_size):
    """Shared attention sub-block: qkv → rotary → cache scatter → paged
    attention → output projection.  Returns (attn_out [T, D], new kv_layer).
    kv_layer: [2, num_blocks, bs, Hkv, Dh]."""
    dtype = jnp.dtype(cfg.dtype)
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    q = _qkv(h, lp_attn["q_proj"], dtype)
    k = _qkv(h, lp_attn["k_proj"], dtype)
    v = _qkv(h, lp_attn["v_proj"], dtype)
    q = _rotary(q, cos, sin, positions)
    k = _rotary(k, cos, sin, positions)
    kv_layer = kv_layer.at[0, blk, off].set(k.astype(kv_layer.dtype))
    kv_layer = kv_layer.at[1, blk, off].set(v.astype(kv_layer.dtype))
    out = _paged_attention(q, kv_layer[0], kv_layer[1], tables_t,
                           positions, block_size,
                           window=getattr(cfg, "sliding_window", 0))
    o = out.reshape(out.shape[0], H * Dh)
    return jnp.einsum("tf,fd->td", o,
                      lp_attn["o_proj"]["kernel"].astype(dtype)), kv_layer


@functools.partial(jax.jit, static_argnames=("cfg", "block_size"),
                   donate_argnums=(1, ))
def llama_ragged_step(params, kv_data, token_ids, positions, seq_slots,
                      block_tables, last_token_idx, *, cfg, block_size):
    """One ragged engine iteration for the Llama family.

    Args:
      params: LlamaModel param tree (``models/llama.py`` naming).
      kv_data: [L, 2, num_blocks, bs, Hkv, Dh] paged cache (donated).
      token_ids/positions/seq_slots: [T] flat batch (padding: slot 0 = the
        reserved garbage block row, position 0).
      block_tables: [max_seqs, maxb] int32.
      last_token_idx: [max_seqs] int32 — buffer index of each slot's last
        scheduled token (logits gather; 0 for idle slots).

    Returns (logits [max_seqs, V] fp32, new kv_data).
    """
    dtype = jnp.dtype(cfg.dtype)
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    eps = cfg.rms_norm_eps
    cos, sin = _rope_freqs(Dh, cfg.max_position_embeddings, cfg.rope_theta)
    cos = jnp.asarray(cos, jnp.float32)
    sin = jnp.asarray(sin, jnp.float32)

    x = params["embed_tokens"]["embedding"][token_ids].astype(dtype)  # [T, D]
    tables_t = block_tables[seq_slots]                       # [T, maxb]
    blk = tables_t[jnp.arange(token_ids.shape[0]),
                   positions // block_size]                  # [T]
    off = positions % block_size

    for l in range(cfg.num_hidden_layers):
        lp = params[f"layers_{l}"]
        mlp = lp["mlp"]
        h = _rmsnorm(x, lp["input_layernorm"]["weight"], eps)
        # scatter this batch's K/V into the paged cache (linear_blocked_kv_
        # rotary analog), then attend against the updated pages
        attn_out, kv_layer = _ragged_attention_block(
            lp["self_attn"], h, kv_data[l], blk, off, tables_t, positions,
            cos, sin, cfg=cfg, block_size=block_size)
        kv_data = kv_data.at[l].set(kv_layer)
        x = x + attn_out
        h2 = _rmsnorm(x, lp["post_attention_layernorm"]["weight"], eps)
        gate = h2 @ mlp["gate_proj"]["kernel"].astype(dtype)
        up = h2 @ mlp["up_proj"]["kernel"].astype(dtype)
        x = x + (jax.nn.silu(gate) * up) @ mlp["down_proj"]["kernel"].astype(
            dtype)

    return _lm_head(params, x, last_token_idx, cfg), kv_data


def _lm_head(params, x, last_token_idx, cfg):
    """logits_gather analog: only each slot's last token reaches the head."""
    eps = cfg.rms_norm_eps
    x = _rmsnorm(x, params["norm"]["weight"], eps)
    xl = x[last_token_idx].astype(jnp.float32)               # [max_seqs, D]
    if cfg.tie_word_embeddings:
        return xl @ params["embed_tokens"]["embedding"].T.astype(jnp.float32)
    return xl @ params["lm_head"]["kernel"].astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("cfg", "block_size"),
                   donate_argnums=(1, ))
def mixtral_ragged_step(params, kv_data, token_ids, positions, seq_slots,
                        block_tables, last_token_idx, *, cfg, block_size):
    """One ragged engine iteration for Mixtral (reference
    ``inference/v2/model_implementations/mixtral/``): Llama attention skeleton
    with the MLP replaced by the exact top-k sparse MoE (``moe_apply`` —
    grouped ``ragged_dot`` over tokens sorted by expert, no token dropping)."""
    from ...models.mixtral import moe_apply

    dtype = jnp.dtype(cfg.dtype)
    eps = cfg.rms_norm_eps
    cos, sin = _rope_freqs(cfg.head_dim, cfg.max_position_embeddings,
                           cfg.rope_theta)
    cos = jnp.asarray(cos, jnp.float32)
    sin = jnp.asarray(sin, jnp.float32)

    x = params["embed_tokens"]["embedding"][token_ids].astype(dtype)
    tables_t = block_tables[seq_slots]
    blk = tables_t[jnp.arange(token_ids.shape[0]),
                   positions // block_size]
    off = positions % block_size

    for l in range(cfg.num_hidden_layers):
        lp = params[f"layers_{l}"]
        h = _rmsnorm(x, lp["input_layernorm"]["weight"], eps)
        attn_out, kv_layer = _ragged_attention_block(
            lp["self_attn"], h, kv_data[l], blk, off, tables_t, positions,
            cos, sin, cfg=cfg, block_size=block_size)
        kv_data = kv_data.at[l].set(kv_layer)
        x = x + attn_out
        h2 = _rmsnorm(x, lp["post_attention_layernorm"]["weight"], eps)
        moe = lp["moe"]
        router_logits = (h2.astype(jnp.float32)
                         @ moe["gate"]["kernel"].astype(jnp.float32))
        x = x + moe_apply(h2, router_logits,
                          moe["w1"].astype(dtype), moe["w2"].astype(dtype),
                          moe["w3"].astype(dtype), cfg.num_experts_per_tok)

    return _lm_head(params, x, last_token_idx, cfg), kv_data


RAGGED_FORWARDS = {"LlamaModel": llama_ragged_step,
                   "MixtralModel": mixtral_ragged_step}
