"""Ragged (paged-KV) model forward (reference
``inference/v2/model_implementations/llama_v2`` + the ragged kernel suite
``kernels/ragged_ops``: linear_blocked_kv_rotary, blocked flash, logits_gather).

One jitted function processes a *flat token buffer* ``[T]`` — the union of
prefill chunks and single decode tokens from many sequences — against the
paged KV cache.  The reference does this with hand-written CUDA (atom builder
+ blocked flash); here the batch metadata (positions, sequence slots, block
tables) turns the same computation into gathers/scatters XLA schedules, with
the attention core a candidate for a Pallas paged kernel (the math below is
already blocked: swap `_paged_attention` for a kernel without touching the
rest).

Token semantics: every token's K/V is written to the cache *before* attention
runs, and each token attends to cache positions ≤ its own — so a multi-token
prefill chunk is causal within itself and sees all earlier chunks, and a
decode token sees the whole prefix.  Exactly FastGen's ragged semantics.
"""

import functools

import jax
import jax.numpy as jnp

from ...models.llama import _rope_freqs


def _rotary(x, cos, sin, positions):
    """x: [T, H, Dh]; positions: [T]."""
    c = cos[positions][:, None, :]
    s = sin[positions][:, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(x.dtype)


def _rmsnorm(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * w).astype(x.dtype)


def _paged_attention(q, k_cache, v_cache, tables_t, positions, block_size,
                     window=0, layout=(0, 0), use_kernel=True,
                     kv_scales=None):
    """q: [T, H, Dh]; caches: [num_blocks, bs, Hkv, Dh]; tables_t: [T, maxb];
    positions: [T]; window: sliding-window size (0 → full causal).
    Returns [T, H, Dh].

    On TPU: the Pallas paged kernel (block pages streamed through VMEM via
    scalar-prefetched table indices).  ``layout=(decode_cap, atom)`` > (0,0)
    means the buffer is region-split by the batch builder: per-token kernel
    for the first ``decode_cap`` rows, atom-tiled kernel (``atom``
    same-sequence rows per tile — much better MXU occupancy for prefill)
    for the rest.  Fallback: XLA gather of each token's block run with
    position masking.

    ``kv_scales=(k_scales, v_scales)`` ([num_blocks, bs, Hkv] f32 each) is
    the quantized-KV read path: the caches hold int8/fp8 rows and only the
    gathered context is dequantized (per-(token, head) scale applied inside
    the same f32 widening the math does anyway).  The Pallas kernel doesn't
    consume scales, so this path always takes the XLA gather."""
    import os
    if (use_kernel and kv_scales is None
            and (jax.default_backend() == "tpu"
                 or os.environ.get("DS_TPU_TEST_PAGED_INTERPRET"))
            and not os.environ.get("DS_TPU_DISABLE_PALLAS_PAGED")):
        from ...ops.pallas.paged_attention import (paged_attention,
                                                   paged_attention_atoms)
        decode_cap, atom = layout
        if atom and q.shape[0] > decode_cap:
            out_d = paged_attention(q[:decode_cap], k_cache, v_cache,
                                    tables_t[:decode_cap],
                                    positions[:decode_cap], window=window) \
                if decode_cap else q[:0]
            out_p = paged_attention_atoms(
                q[decode_cap:], k_cache, v_cache, tables_t[decode_cap:],
                positions[decode_cap:], atom, window=window)
            return jnp.concatenate([out_d, out_p], axis=0)
        return paged_attention(q, k_cache, v_cache, tables_t, positions,
                               window=window)
    T, H, Dh = q.shape
    Hkv = k_cache.shape[2]
    maxb = tables_t.shape[1]
    ctx = maxb * block_size
    k_ctx = k_cache[tables_t].reshape(T, ctx, Hkv, Dh)
    v_ctx = v_cache[tables_t].reshape(T, ctx, Hkv, Dh)
    if kv_scales is not None:
        # dequant-on-read: per-(token, head) scales broadcast over Dh
        ks, vs = kv_scales
        k_ctx = (k_ctx.astype(jnp.float32)
                 * ks[tables_t].reshape(T, ctx, Hkv)[:, :, :, None])
        v_ctx = (v_ctx.astype(jnp.float32)
                 * vs[tables_t].reshape(T, ctx, Hkv)[:, :, :, None])
    g = H // Hkv
    qg = q.reshape(T, Hkv, g, Dh).astype(jnp.float32)
    scores = jnp.einsum("tkgd,tckd->tkgc", qg,
                        k_ctx.astype(jnp.float32)) * (Dh**-0.5)
    pos_ctx = jnp.arange(ctx)[None, None, None, :]
    pos_q = positions[:, None, None, None]
    mask = pos_ctx <= pos_q
    if window:
        mask &= pos_ctx > pos_q - window
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("tkgc,tckd->tkgd", probs, v_ctx.astype(jnp.float32))
    return out.reshape(T, H, Dh).astype(q.dtype)


def _qkv(h, proj, dtype):
    """DenseGeneral [T, D] → [T, H, Dh] with optional bias (Qwen2)."""
    y = jnp.einsum("td,dhk->thk", h, proj["kernel"].astype(dtype))
    if "bias" in proj:
        y = y + proj["bias"].astype(dtype)
    return y


def _lin(h, p, dtype):
    """Plain linear with optional bias."""
    y = h @ p["kernel"].astype(dtype)
    return y + p["bias"].astype(dtype) if "bias" in p else y


def _attn_cfg_view(cfg, sliding_window=0):
    """The subset of model config the shared attention block reads —
    one adapter for every non-llama architecture."""
    import types
    return types.SimpleNamespace(
        num_attention_heads=cfg.num_attention_heads, head_dim=cfg.head_dim,
        sliding_window=sliding_window, dtype=cfg.dtype)


def _head_logits(params, x, last_token_idx, embed_key="embed_tokens"):
    """logits_gather epilogue shared by the zoo steps: gather each slot's
    last token, tied-embedding or lm_head projection (with optional bias)."""
    xl = x[last_token_idx].astype(jnp.float32)
    if "lm_head" in params:
        logits = xl @ params["lm_head"]["kernel"].astype(jnp.float32)
        if "bias" in params["lm_head"]:
            logits = logits + params["lm_head"]["bias"].astype(jnp.float32)
        return logits
    logits = xl @ params[embed_key]["embedding"].T.astype(jnp.float32)
    if "lm_head_bias" in params:  # tied phi: weight shared, bias live
        logits = logits + params["lm_head_bias"].astype(jnp.float32)
    return logits


def _kv_layer(kv_data, l):
    """Layer ``l`` view of the cache pytree: an array slice on the fp path,
    a ``(data_l, scales_l)`` pair on the quantized path."""
    if isinstance(kv_data, tuple):
        data, scales = kv_data
        return (data[l], scales[l])
    return kv_data[l]


def _kv_set(kv_data, l, kv_layer):
    """Write layer ``l`` back into the cache pytree (inverse of
    :func:`_kv_layer`)."""
    if isinstance(kv_data, tuple):
        data, scales = kv_data
        layer_data, layer_scales = kv_layer
        return (data.at[l].set(layer_data), scales.at[l].set(layer_scales))
    return kv_data.at[l].set(kv_layer)


def _ragged_attention_block(lp_attn, h, kv_layer, blk, off, tables_t,
                            positions, cos, sin, *, cfg, block_size,
                            rotary=True, rotary_dim=None,
                            layout=(0, 0), use_kernel=True, kv_dtype=None):
    """Shared attention sub-block: qkv → rotary → cache scatter → paged
    attention → output projection.  Returns (attn_out [T, D], new kv_layer).
    kv_layer: [2, num_blocks, bs, Hkv, Dh] — or, with ``kv_dtype`` set, the
    quantized pair ``(data [2, nb, bs, Hkv, Dh] narrow, scales [2, nb, bs, Hkv]
    f32)``: K/V rows are encoded once on the scatter write and dequantized
    on read inside the paged attention (``kv_codec.py``).  ``rotary_dim`` <
    head_dim → partial rotary (phi family)."""
    dtype = jnp.dtype(cfg.dtype)
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    q = _qkv(h, lp_attn["q_proj"], dtype)
    k = _qkv(h, lp_attn["k_proj"], dtype)
    v = _qkv(h, lp_attn["v_proj"], dtype)
    if rotary:
        if rotary_dim and rotary_dim < Dh:
            rot = lambda x: jnp.concatenate(
                [_rotary(x[..., :rotary_dim], cos, sin, positions),
                 x[..., rotary_dim:]], axis=-1)
            q, k = rot(q), rot(k)
        else:
            q = _rotary(q, cos, sin, positions)
            k = _rotary(k, cos, sin, positions)
    if kv_dtype is None:
        kv_layer = kv_layer.at[0, blk, off].set(k.astype(kv_layer.dtype))
        kv_layer = kv_layer.at[1, blk, off].set(v.astype(kv_layer.dtype))
        k_cache, v_cache = kv_layer[0], kv_layer[1]
        kv_scales = None
    else:
        from .kv_codec import codec
        encode, _ = codec(kv_dtype)
        data, scales = kv_layer
        qk, sk = encode(k)          # [T, Hkv, Dh] narrow, [T, Hkv] f32
        qv, sv = encode(v)
        data = data.at[0, blk, off].set(qk)
        data = data.at[1, blk, off].set(qv)
        scales = scales.at[0, blk, off].set(sk)
        scales = scales.at[1, blk, off].set(sv)
        kv_layer = (data, scales)
        k_cache, v_cache = data[0], data[1]
        kv_scales = (scales[0], scales[1])
    out = _paged_attention(q, k_cache, v_cache, tables_t,
                           positions, block_size,
                           window=getattr(cfg, "sliding_window", 0),
                           layout=layout, use_kernel=use_kernel,
                           kv_scales=kv_scales)
    o = out.reshape(out.shape[0], H * Dh)
    o = jnp.einsum("tf,fd->td", o, lp_attn["o_proj"]["kernel"].astype(dtype))
    if "bias" in lp_attn["o_proj"]:
        o = o + lp_attn["o_proj"]["bias"].astype(dtype)
    return o, kv_layer


@functools.partial(jax.jit, static_argnames=("cfg", "block_size", "layout",
                                             "use_kernel", "kv_dtype"),
                   donate_argnums=(1, ))
def llama_ragged_step(params, kv_data, token_ids, positions, seq_slots,
                      block_tables, last_token_idx, *, cfg, block_size,
                      layout=(0, 0), use_kernel=True, kv_dtype=None):
    """One ragged engine iteration for the Llama family.

    Args:
      params: LlamaModel param tree (``models/llama.py`` naming).
      kv_data: [L, 2, num_blocks, bs, Hkv, Dh] paged cache (donated).
      token_ids/positions/seq_slots: [T] flat batch (padding: slot 0 = the
        reserved garbage block row, position 0).
      block_tables: [max_seqs, maxb] int32.
      last_token_idx: [max_seqs] int32 — buffer index of each slot's last
        scheduled token (logits gather; 0 for idle slots).

    Returns (logits [max_seqs, V] fp32, new kv_data).
    """
    dtype = jnp.dtype(cfg.dtype)
    H, Hkv, Dh = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    eps = cfg.rms_norm_eps
    cos, sin = _rope_freqs(Dh, cfg.max_position_embeddings, cfg.rope_theta,
                           cfg.rope_scaling)
    cos = jnp.asarray(cos, jnp.float32)
    sin = jnp.asarray(sin, jnp.float32)

    x = params["embed_tokens"]["embedding"][token_ids].astype(dtype)  # [T, D]
    tables_t = block_tables[seq_slots]                       # [T, maxb]
    blk = tables_t[jnp.arange(token_ids.shape[0]),
                   positions // block_size]                  # [T]
    off = positions % block_size

    for l in range(cfg.num_hidden_layers):
        lp = params[f"layers_{l}"]
        mlp = lp["mlp"]
        h = _rmsnorm(x, lp["input_layernorm"]["weight"], eps)
        # scatter this batch's K/V into the paged cache (linear_blocked_kv_
        # rotary analog), then attend against the updated pages
        attn_out, kv_layer = _ragged_attention_block(
            lp["self_attn"], h, _kv_layer(kv_data, l), blk, off, tables_t, positions,
            cos, sin, cfg=cfg, block_size=block_size, layout=layout,
            use_kernel=use_kernel, kv_dtype=kv_dtype)
        kv_data = _kv_set(kv_data, l, kv_layer)
        x = x + attn_out
        h2 = _rmsnorm(x, lp["post_attention_layernorm"]["weight"], eps)
        gate = h2 @ mlp["gate_proj"]["kernel"].astype(dtype)
        up = h2 @ mlp["up_proj"]["kernel"].astype(dtype)
        x = x + (jax.nn.silu(gate) * up) @ mlp["down_proj"]["kernel"].astype(
            dtype)

    return _lm_head(params, x, last_token_idx, cfg), kv_data


def _lm_head(params, x, last_token_idx, cfg):
    """logits_gather analog: only each slot's last token reaches the head."""
    eps = cfg.rms_norm_eps
    x = _rmsnorm(x, params["norm"]["weight"], eps)
    xl = x[last_token_idx].astype(jnp.float32)               # [max_seqs, D]
    if cfg.tie_word_embeddings:
        return xl @ params["embed_tokens"]["embedding"].T.astype(jnp.float32)
    return xl @ params["lm_head"]["kernel"].astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("cfg", "block_size", "layout",
                                             "use_kernel", "kv_dtype"),
                   donate_argnums=(1, ))
def mixtral_ragged_step(params, kv_data, token_ids, positions, seq_slots,
                        block_tables, last_token_idx, *, cfg, block_size,
                      layout=(0, 0), use_kernel=True, kv_dtype=None):
    """One ragged engine iteration for Mixtral (reference
    ``inference/v2/model_implementations/mixtral/``): Llama attention skeleton
    with the MLP replaced by the exact top-k sparse MoE (``moe_apply`` —
    grouped ``ragged_dot`` over tokens sorted by expert, no token dropping)."""
    from ...models.mixtral import moe_apply

    dtype = jnp.dtype(cfg.dtype)
    eps = cfg.rms_norm_eps
    cos, sin = _rope_freqs(cfg.head_dim, cfg.max_position_embeddings,
                           cfg.rope_theta, cfg.rope_scaling)
    cos = jnp.asarray(cos, jnp.float32)
    sin = jnp.asarray(sin, jnp.float32)

    x = params["embed_tokens"]["embedding"][token_ids].astype(dtype)
    tables_t = block_tables[seq_slots]
    blk = tables_t[jnp.arange(token_ids.shape[0]),
                   positions // block_size]
    off = positions % block_size

    for l in range(cfg.num_hidden_layers):
        lp = params[f"layers_{l}"]
        h = _rmsnorm(x, lp["input_layernorm"]["weight"], eps)
        attn_out, kv_layer = _ragged_attention_block(
            lp["self_attn"], h, _kv_layer(kv_data, l), blk, off, tables_t, positions,
            cos, sin, cfg=cfg, block_size=block_size, layout=layout,
            use_kernel=use_kernel, kv_dtype=kv_dtype)
        kv_data = _kv_set(kv_data, l, kv_layer)
        x = x + attn_out
        h2 = _rmsnorm(x, lp["post_attention_layernorm"]["weight"], eps)
        moe = lp["moe"]
        router_logits = (h2.astype(jnp.float32)
                         @ moe["gate"]["kernel"].astype(jnp.float32))
        moe_out = moe_apply(h2, router_logits,
                            moe["w1"].astype(dtype), moe["w2"].astype(dtype),
                            moe["w3"].astype(dtype), cfg.num_experts_per_tok,
                            norm_topk=getattr(cfg, "norm_topk_prob", True))
        if "shared_gate_proj" in moe:  # qwen2-moe dense shared expert
            g = h2 @ moe["shared_gate_proj"]["kernel"].astype(dtype)
            u = h2 @ moe["shared_up_proj"]["kernel"].astype(dtype)
            sh = (jax.nn.silu(g) * u) @ moe["shared_down_proj"][
                "kernel"].astype(dtype)
            mix = jax.nn.sigmoid(
                h2.astype(jnp.float32)
                @ moe["shared_expert_gate"]["kernel"].astype(jnp.float32))
            moe_out = moe_out + (mix * sh.astype(jnp.float32)).astype(
                moe_out.dtype)
        x = x + moe_out

    return _lm_head(params, x, last_token_idx, cfg), kv_data


def _layernorm(x, p, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * p["scale"]
            + p["bias"]).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("cfg", "block_size", "layout",
                                             "use_kernel", "kv_dtype"),
                   donate_argnums=(1, ))
def falcon_ragged_step(params, kv_data, token_ids, positions, seq_slots,
                       block_tables, last_token_idx, *, cfg, block_size,
                      layout=(0, 0), use_kernel=True, kv_dtype=None):
    """One ragged engine iteration for Falcon (reference
    ``inference/v2/model_implementations/falcon/``): parallel-block layout —
    attention and the GELU MLP read the same layernormed input and add into
    the residual together."""
    dtype = jnp.dtype(cfg.dtype)
    eps = cfg.layer_norm_epsilon
    cos, sin = _rope_freqs(cfg.head_dim, cfg.max_position_embeddings,
                           cfg.rope_theta)
    cos = jnp.asarray(cos, jnp.float32)
    sin = jnp.asarray(sin, jnp.float32)

    x = params["word_embeddings"]["embedding"][token_ids].astype(dtype)
    tables_t = block_tables[seq_slots]
    blk = tables_t[jnp.arange(token_ids.shape[0]), positions // block_size]
    off = positions % block_size
    acfg = _attn_cfg_view(cfg)

    for l in range(cfg.num_hidden_layers):
        lp = params[f"h_{l}"]
        if cfg.new_decoder_architecture:
            h_attn = _layernorm(x, lp["ln_attn"], eps)
            h_mlp = _layernorm(x, lp["ln_mlp"], eps)
        else:
            h_attn = h_mlp = _layernorm(x, lp["input_layernorm"], eps)
        attn_params = {"q_proj": lp["q_proj"], "k_proj": lp["k_proj"],
                       "v_proj": lp["v_proj"], "o_proj": lp["dense"]}
        attn_out, kv_layer = _ragged_attention_block(
            attn_params, h_attn, _kv_layer(kv_data, l), blk, off, tables_t,
            positions,
            cos, sin, cfg=acfg, block_size=block_size, layout=layout,
            use_kernel=use_kernel, kv_dtype=kv_dtype)
        kv_data = _kv_set(kv_data, l, kv_layer)
        if not cfg.parallel_attn:
            x = x + attn_out
            h_mlp = _layernorm(x, lp["post_attention_layernorm"], eps)
        mlp = _lin(jax.nn.gelu(_lin(h_mlp, lp["dense_h_to_4h"], dtype)),
                   lp["dense_4h_to_h"], dtype)
        x = (x + attn_out + mlp) if cfg.parallel_attn else (x + mlp)

    x = _layernorm(x, params["ln_f"], eps)
    return _head_logits(params, x, last_token_idx,
                        embed_key="word_embeddings"), kv_data


@functools.partial(jax.jit, static_argnames=("cfg", "block_size", "layout",
                                             "use_kernel", "kv_dtype"),
                   donate_argnums=(1, ))
def opt_ragged_step(params, kv_data, token_ids, positions, seq_slots,
                    block_tables, last_token_idx, *, cfg, block_size,
                      layout=(0, 0), use_kernel=True, kv_dtype=None):
    """One ragged engine iteration for OPT (reference
    ``inference/v2/model_implementations/opt/``): learned positions (+2
    offset), pre-LN blocks, ReLU MLP, no rotary."""
    from ...models.opt import OPT_POSITION_OFFSET

    dtype = jnp.dtype(cfg.dtype)
    eps = cfg.layer_norm_eps

    x = (params["embed_tokens"]["embedding"][token_ids]
         + params["embed_positions"]["embedding"][
             positions + OPT_POSITION_OFFSET]).astype(dtype)
    tables_t = block_tables[seq_slots]
    blk = tables_t[jnp.arange(token_ids.shape[0]), positions // block_size]
    off = positions % block_size
    acfg = _attn_cfg_view(cfg)

    for l in range(cfg.num_hidden_layers):
        lp = params[f"layers_{l}"]
        h = _layernorm(x, lp["self_attn_layer_norm"], eps) \
            if cfg.do_layer_norm_before else x
        attn_params = {"q_proj": lp["q_proj"], "k_proj": lp["k_proj"],
                       "v_proj": lp["v_proj"], "o_proj": lp["out_proj"]}
        attn_out, kv_layer = _ragged_attention_block(
            attn_params, h, _kv_layer(kv_data, l), blk, off, tables_t, positions,
            None, None, cfg=acfg, block_size=block_size, rotary=False,
            layout=layout, use_kernel=use_kernel, kv_dtype=kv_dtype)
        kv_data = _kv_set(kv_data, l, kv_layer)
        x = x + attn_out
        if not cfg.do_layer_norm_before:
            x = _layernorm(x, lp["self_attn_layer_norm"], eps)
        h = _layernorm(x, lp["final_layer_norm"], eps) \
            if cfg.do_layer_norm_before else x
        x = x + _lin(jax.nn.relu(_lin(h, lp["fc1"], dtype)), lp["fc2"],
                     dtype)
        if not cfg.do_layer_norm_before:
            x = _layernorm(x, lp["final_layer_norm"], eps)

    if cfg.do_layer_norm_before:
        x = _layernorm(x, params["final_layer_norm"], eps)
    return _head_logits(params, x, last_token_idx), kv_data


@functools.partial(jax.jit, static_argnames=("cfg", "block_size", "layout",
                                             "use_kernel", "kv_dtype"),
                   donate_argnums=(1, ))
def phi_ragged_step(params, kv_data, token_ids, positions, seq_slots,
                    block_tables, last_token_idx, *, cfg, block_size,
                      layout=(0, 0), use_kernel=True, kv_dtype=None):
    """One ragged engine iteration for Phi-2 (reference
    ``inference/v2/model_implementations/phi/``): parallel block, partial
    rotary, LayerNorm, biased linears (incl. lm_head)."""
    dtype = jnp.dtype(cfg.dtype)
    eps = cfg.layer_norm_eps
    rd = cfg.rotary_dim
    cos, sin = _rope_freqs(rd, cfg.max_position_embeddings, cfg.rope_theta)
    cos = jnp.asarray(cos, jnp.float32)
    sin = jnp.asarray(sin, jnp.float32)

    x = params["embed_tokens"]["embedding"][token_ids].astype(dtype)
    tables_t = block_tables[seq_slots]
    blk = tables_t[jnp.arange(token_ids.shape[0]), positions // block_size]
    off = positions % block_size
    acfg = _attn_cfg_view(cfg)

    for l in range(cfg.num_hidden_layers):
        lp = params[f"layers_{l}"]
        h = _layernorm(x, lp["input_layernorm"], eps)
        attn_params = {"q_proj": lp["q_proj"], "k_proj": lp["k_proj"],
                       "v_proj": lp["v_proj"], "o_proj": lp["dense"]}
        attn_out, kv_layer = _ragged_attention_block(
            attn_params, h, _kv_layer(kv_data, l), blk, off, tables_t, positions,
            cos, sin, cfg=acfg, block_size=block_size, rotary_dim=rd,
            layout=layout, use_kernel=use_kernel, kv_dtype=kv_dtype)
        kv_data = _kv_set(kv_data, l, kv_layer)
        mlp = _lin(jax.nn.gelu(_lin(h, lp["fc1"], dtype)), lp["fc2"], dtype)
        x = x + attn_out + mlp

    x = _layernorm(x, params["final_layernorm"], eps)
    return _head_logits(params, x, last_token_idx), kv_data


RAGGED_FORWARDS = {"LlamaModel": llama_ragged_step,
                   "MixtralModel": mixtral_ragged_step,
                   "FalconModel": falcon_ragged_step,
                   "OPTModel": opt_ragged_step,
                   "PhiModel": phi_ragged_step}


def _device_sample(logits, key, temperature, top_k, top_p):
    """Per-row categorical with the engine's generate options (temperature /
    top-k / nucleus top-p), all on device.  ``top_k`` is static (shapes);
    temperature/top_p are traced scalars.  Same filtering semantics as the
    host ``_sample_row``: smallest prefix reaching ``top_p``, always ≥ 1
    candidate."""
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    p = jax.nn.softmax(logits, axis=-1)
    sp = jnp.sort(p, axis=-1)[:, ::-1]                      # descending
    csum = jnp.cumsum(sp, axis=-1)
    # per row: the smallest kept probability of the nucleus prefix
    kept = jnp.where(csum - sp < top_p, sp, jnp.inf)
    thresh = jnp.min(kept, axis=-1, keepdims=True)
    logits = jnp.where(p < thresh, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("step_fn", "cfg", "block_size", "k", "use_kernel",
                     "sample", "top_k", "kv_dtype"),
    donate_argnums=(1, ))
def decode_burst(params, kv_data, tok0, pos0, active, block_tables, *,
                 step_fn, cfg, block_size, k, use_kernel=True,
                 sample=False, key=None, temperature=1.0, top_k=0,
                 top_p=1.0, kv_dtype=None):
    """``k`` greedy decode iterations in ONE compiled program.

    The per-step serving loop pays a host round-trip per generated token
    (fetch argmax → rebuild the ragged batch → re-upload).  When every
    running sequence is in pure decode, that loop is a fixed-point the
    device can run alone: a ``lax.scan`` feeds each step's argmax back as
    the next step's input token, and the host fetches ``k`` tokens per
    sequence in one transfer.  TPU answer to the role CUDA graphs play in
    the reference's decode path (``inference/engine.py:519``
    ``_create_cuda_graph``) — here the whole multi-token loop is one XLA
    program, not a replayed capture.

    Layout: row ``i`` of the [max_seqs]-token batch belongs to slot ``i``
    (``last_token_idx = arange``); idle rows carry ``active=False`` and are
    steered to slot 0, whose block-table row is the reserved garbage block.
    Greedy only — sampling keeps the host loop (host RNG semantics).

    Args:
      tok0/pos0/active: [max_seqs] — each active slot's pending token and
        its position; block capacity for ``pos0 + k`` must be pre-ensured.
      step_fn: a RAGGED_FORWARDS value (the jitted wrapper's underlying
        function is inlined into the scan body).

    With ``sample=True`` each iteration draws from the temperature/top-k/
    top-p-filtered distribution with the jax PRNG ``key`` (split per
    iteration) instead of argmax — seed-deterministic, but a DIFFERENT
    stream than the host loop's numpy Generator, which is why the engine
    gates it behind ``decode_burst_sampling``.

    Returns ([k, max_seqs] int32 tokens (one per iteration), new kv).
    """
    n = tok0.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    slots = jnp.where(active, rows, 0)
    inner = getattr(step_fn, "__wrapped__", step_fn)
    if key is None:
        key = jax.random.PRNGKey(0)

    def body(carry, _):
        kv, toks, pos, key = carry
        logits, kv = inner(params, kv, jnp.where(active, toks, 0),
                           jnp.where(active, pos, 0), slots, block_tables,
                           rows, cfg=cfg, block_size=block_size,
                           layout=(0, 0), use_kernel=use_kernel,
                           kv_dtype=kv_dtype)
        if sample:
            key, sub = jax.random.split(key)
            nxt = _device_sample(logits, sub, temperature, top_k, top_p)
        else:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (kv, nxt, pos + 1, key), nxt

    (kv_data, _, _, _), toks_out = jax.lax.scan(
        body, (kv_data, tok0.astype(jnp.int32), pos0.astype(jnp.int32),
               key), None, length=k)
    return toks_out, kv_data
