"""Inference v2 — FastGen-style ragged continuous batching (reference
``deepspeed/inference/v2/``): blocked KV cache, token-budget scheduling,
put/query/flush serving API."""

from .config_v2 import RaggedInferenceEngineConfig
from .engine_v2 import InferenceEngineV2
from .engine_factory import build_engine_from_checkpoint, build_hf_engine
from .ragged import (BlockedAllocator, BlockedKVCache, DSSequenceDescriptor,
                     DSStateManager, KVCacheExhausted)
