"""Inference v2 — FastGen-style ragged continuous batching (reference
``deepspeed/inference/v2/``): blocked KV cache, token-budget scheduling,
put/query/flush serving API."""

from .config_v2 import RaggedInferenceEngineConfig
from .engine_v2 import InferenceEngineV2
from .ragged import (BlockedAllocator, BlockedKVCache, DSSequenceDescriptor,
                     DSStateManager)
