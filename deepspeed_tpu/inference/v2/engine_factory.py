"""Engine factory (reference ``inference/v2/engine_factory.py``:
``build_hf_engine`` ``:69``, ``build_engine_from_ds_checkpoint`` ``:32``).

``build_hf_engine(path)`` turns a local HF checkpoint directory into a
serving-ready :class:`InferenceEngineV2` — config.json → model config,
safetensors → flax params, ragged forward selected by architecture.
"""

from typing import Optional, Union

from .checkpoint import (CheckpointEngineBase, HuggingFaceCheckpointEngine,
                         InMemoryModelEngine)
from .config_v2 import RaggedInferenceEngineConfig
from .engine_v2 import InferenceEngineV2
from .model_implementations import build_model_and_params
from .model_implementations.hf_builders import V1_ONLY_MODEL_TYPES


def build_hf_engine(path: str,
                    engine_config: Optional[Union[
                        dict, RaggedInferenceEngineConfig]] = None,
                    debug_level: int = 0,
                    **kwargs) -> InferenceEngineV2:
    """Serve a HuggingFace checkpoint (reference ``engine_factory.py:69``).

    ``path``: local model directory (config.json + safetensors / .bin).
    """
    if engine_config is None:
        engine_config = RaggedInferenceEngineConfig(**kwargs)
    elif isinstance(engine_config, dict):
        engine_config = RaggedInferenceEngineConfig(**{**engine_config,
                                                       **kwargs})
    checkpoint = HuggingFaceCheckpointEngine(path)
    from .ragged_forward import RAGGED_FORWARDS
    model_type = checkpoint.model_config.get("model_type", "llama")
    if model_type in V1_ONLY_MODEL_TYPES:
        # ingestable for v1 injection but no ragged forward exists — fail
        # BEFORE ingesting gigabytes of weights
        raise ValueError(
            f"{model_type!r} is served by the v1 engine "
            "(deepspeed_tpu.init_inference via "
            "module_inject.replace_transformer_layer), not FastGen v2 — "
            f"no ragged forward is registered (have: "
            f"{sorted(RAGGED_FORWARDS)})")
    model, params = build_model_and_params(checkpoint,
                                           dtype=engine_config.dtype)
    return InferenceEngineV2(model, params=params, config=engine_config)


class _ConfiguredCheckpoint(CheckpointEngineBase):
    """Pairs any checkpoint engine with an explicit model config (some
    engines expose ``model_config`` as a read-only property — never assign
    onto them)."""

    def __init__(self, inner, model_config):
        self._inner = inner
        self.model_config = model_config

    def parameters(self):
        return self._inner.parameters()


def build_engine_from_checkpoint(checkpoint: CheckpointEngineBase,
                                 model_config: dict,
                                 engine_config: Optional[
                                     RaggedInferenceEngineConfig] = None
                                 ) -> InferenceEngineV2:
    """Build from any checkpoint engine + an HF-style config dict (reference
    ``build_engine_from_ds_checkpoint``)."""
    if engine_config is None:
        engine_config = RaggedInferenceEngineConfig()
    model, params = build_model_and_params(
        _ConfiguredCheckpoint(checkpoint, model_config),
        dtype=engine_config.dtype)
    return InferenceEngineV2(model, params=params, config=engine_config)
