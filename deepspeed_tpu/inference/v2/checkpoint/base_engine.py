"""Checkpoint engine ABC (reference
``inference/v2/checkpoint/base_engine.py``): one method, ``parameters()``,
yielding ``(name, numpy array)`` in the source checkpoint's naming."""

from abc import ABC, abstractmethod
from typing import Iterable, Tuple

import numpy as np


class CheckpointEngineBase(ABC):

    @abstractmethod
    def parameters(self) -> Iterable[Tuple[str, np.ndarray]]:
        """Yield ``(param_name, value)`` for every parameter in the
        checkpoint.  Values are host numpy arrays (the model builder decides
        device placement and sharding)."""
        ...
