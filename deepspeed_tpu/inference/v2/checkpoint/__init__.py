"""Checkpoint ingestion for inference v2 (reference
``inference/v2/checkpoint/``): pluggable engines yielding ``(name, array)``
pairs, plus the HuggingFace safetensors/torch loader."""

from .base_engine import CheckpointEngineBase
from .in_memory_engine import InMemoryModelEngine
from .huggingface_engine import HuggingFaceCheckpointEngine
