"""HuggingFace checkpoint ingestion (reference
``inference/v2/checkpoint/huggingface_engine.py:16``).

Reads a *local* HF model directory (zero-egress environment: no hub
downloads) and yields ``(name, numpy)`` pairs from, in preference order:

1. ``model.safetensors.index.json`` → sharded safetensors
2. ``model.safetensors`` (or any ``*.safetensors`` glob)
3. ``pytorch_model.bin[.index.json]`` → ``torch.load`` (cpu)

Safetensors are read with ``safetensors.numpy`` — no torch in the loop, and
bf16 tensors arrive as ml_dtypes bfloat16 without an fp32 detour.
"""

import glob
import json
import os
from typing import Iterable, Tuple

import numpy as np

from ....utils.logging import logger
from .base_engine import CheckpointEngineBase


class HuggingFaceCheckpointEngine(CheckpointEngineBase):

    def __init__(self, model_name_or_path: str, auth_token: str = None,
                 **hf_kwargs):
        if not os.path.isdir(model_name_or_path):
            raise ValueError(
                f"{model_name_or_path!r} is not a local directory — this "
                "environment has no network egress; download the checkpoint "
                "first (reference engine falls back to snapshot_download)")
        self.model_name_or_path = model_name_or_path
        self._config = None

    @property
    def model_config(self) -> dict:
        """Parsed ``config.json``."""
        if self._config is None:
            path = os.path.join(self.model_name_or_path, "config.json")
            with open(path) as f:
                self._config = json.load(f)
        return self._config

    def _checkpoint_files(self):
        root = self.model_name_or_path
        for index_name, kind in (("model.safetensors.index.json", "st"),
                                 ("pytorch_model.bin.index.json", "pt")):
            index = os.path.join(root, index_name)
            if os.path.exists(index):
                with open(index) as f:
                    weight_map = json.load(f)["weight_map"]
                files = sorted({os.path.join(root, v)
                                for v in weight_map.values()})
                return files, kind
        st = sorted(glob.glob(os.path.join(root, "*.safetensors")))
        if st:
            return st, "st"
        pt = sorted(glob.glob(os.path.join(root, "pytorch_model*.bin")))
        if pt:
            return pt, "pt"
        raise FileNotFoundError(
            f"no safetensors or pytorch_model.bin under {root}")

    def parameters(self) -> Iterable[Tuple[str, np.ndarray]]:
        files, kind = self._checkpoint_files()
        logger.info(f"HF checkpoint: {len(files)} {kind} shard(s) from "
                    f"{self.model_name_or_path}")
        if kind == "st":
            from safetensors import safe_open
            for path in files:
                with safe_open(path, framework="np") as f:
                    for name in f.keys():
                        yield name, f.get_tensor(name)
        else:
            import torch
            for path in files:
                state = torch.load(path, map_location="cpu",
                                   weights_only=True)
                for name, tensor in state.items():
                    t = tensor.detach()
                    if t.dtype == torch.bfloat16:
                        import ml_dtypes
                        yield name, t.view(torch.uint16).numpy().view(
                            ml_dtypes.bfloat16)
                    else:
                        yield name, t.numpy()
