"""In-memory checkpoint engine (reference
``inference/v2/checkpoint/in_memory_engine.py``): wraps an already-loaded
state dict / param tree for the model builders."""

from typing import Iterable, Tuple

import numpy as np

from .base_engine import CheckpointEngineBase


class InMemoryModelEngine(CheckpointEngineBase):

    def __init__(self, state_dict):
        """``state_dict``: mapping param name → array-like (torch tensors
        are detached to numpy)."""
        self._state = state_dict

    def parameters(self) -> Iterable[Tuple[str, np.ndarray]]:
        for name, value in self._state.items():
            if hasattr(value, "detach"):  # torch tensor
                value = value.detach().to("cpu").float().numpy()
            yield name, np.asarray(value)
