"""Weight-only quantized serving helpers shared by the v1 and v2 engines
(reference ``inference/quantization``): 2-D+ float weights live as
blockwise int8/int4 wire format + scales; dequantization is traced inside
the serving program so fp copies exist only transiently per step.
"""

import jax
import jax.numpy as jnp

from ..ops.pallas.quantizer import dequantize_blockwise, quantize_blockwise
from ..runtime.zero.partition import path_str
from ..utils.logging import log_dist, logger

#: quantization_mode spellings (reference config_v2.py) → bits
MODES = {"int8": 8, "int4": 4, "q8": 8, "q4": 4}

# one TPU lane row — re-exported so existing imports keep working; the
# canonical definition lives with the config defaults derived from it
from .config import LANE_GROUP  # noqa: E402


def resolve_mode(mode):
    """quantization_mode string → bits, or a clear error for modes whose
    wire format we don't serve (e.g. the reference's CUDA-only
    ``wf6af16`` FP6 path — fp6 tensors exist in ops/fp_quantizer but the
    serving integration is int-only for now)."""
    if mode is None:
        return None
    bits = MODES.get(str(mode).lower())
    if bits is None:
        raise NotImplementedError(
            f"quantization_mode={mode!r} is not served here; supported: "
            f"{sorted(MODES)} (fp6/fp8 wire formats exist in "
            "ops/fp_quantizer but only int4/int8 serving is wired)")
    return bits


def is_quantized_leaf(x):
    return isinstance(x, dict) and "__q__" in x


def quantize_tree(params, bits, group_size=LANE_GROUP):
    """Returns (tree with ``{"__q__", "__s__"}`` wire-format dicts for 2-D+
    float leaves, meta dict keyed by path).  Static meta stays out-of-band
    so the tree can cross jit boundaries."""
    if group_size and int(group_size) < LANE_GROUP:
        logger.warning(
            "quant group_size=%s below the TPU lane width; the blockwise "
            "quantizer runs at group %d", group_size, LANE_GROUP)
    meta_out = {}
    n_q = 0

    def maybe_q(kp, x):
        nonlocal n_q
        if (hasattr(x, "ndim") and x.ndim >= 2
                and jnp.issubdtype(x.dtype, jnp.floating)):
            q, s, meta = quantize_blockwise(
                x, num_bits=bits,
                group_size=max(LANE_GROUP, int(group_size or LANE_GROUP)))
            meta_out[path_str(kp)] = meta
            n_q += 1
            return {"__q__": q, "__s__": s}
        return x

    out = jax.tree_util.tree_map_with_path(maybe_q, params)
    log_dist(f"weight-only quant: {n_q} weight tensors stored as "
             f"int{bits} wire format", ranks=[0])
    return out, meta_out


def dequantize_tree(params, meta, dtype):
    """Inverse of :func:`quantize_tree`; traceable (called inside jit)."""

    def dq(kp, x):
        if not is_quantized_leaf(x):
            return x
        m = meta[path_str(kp)]
        return dequantize_blockwise(x["__q__"], x["__s__"],
                                    m).astype(dtype)

    return jax.tree_util.tree_map_with_path(dq, params,
                                            is_leaf=is_quantized_leaf)
