"""Measured-ground-truth priors for the model-based tuner.

Reference ``autotuning/tuner/model_based_tuner.py:19`` starts its cost
model cold — every tuning session re-measures points a previous on-chip
sweep already paid for.  Here trustworthy records from ``.bench_runs/``
(the ladder/sweep artifacts ``tools/bench_retry.sh`` +
``tools/onchip_sweeps.sh`` write, summarized by ``tools/fold_sweeps.py``)
seed ``ModelBasedTuner``'s regression, so TPU tuning starts from measured
ground truth and its FIRST proposal is the best measured config.
"""

import glob
import json
import os
import re

from ..utils.logging import logger

# Trust gate for recorded bench lines — the single source of truth shared
# with bench.py's _untrustworthy: a partial or fallback measurement must
# never be cited, folded, or become a tuning prior.
UNTRUSTED_MARKERS = ("partial", "warmup-estimate", "timing-implausible",
                     "backend=cpu", "cpu-fallback")


def untrustworthy(rec):
    """Why a recorded bench line must not be trusted, or None if it is a
    full, plausible measurement."""
    u = rec.get("unit", "")
    for m in UNTRUSTED_MARKERS:
        if m in u:
            return m
    return None


def _trusted(rec):
    return untrustworthy(rec) is None


def record_to_prior(rec):
    """One bench JSON record → {"ds_config": ..., "throughput": ...} or
    None.  The device bench encodes its config in the unit string
    (``B=<mbs> S=<seq> …``); stage/gas follow the bench's fixed config."""
    if not isinstance(rec, dict) or "metric" in rec and \
            not str(rec.get("metric", "")).startswith("llama_train"):
        return None
    if not _trusted(rec):
        return None
    m = re.search(r"\bB=(\d+)\b", rec.get("unit", ""))
    if m is None or not rec.get("value"):
        return None
    return {
        "ds_config": {
            "train_micro_batch_size_per_gpu": int(m.group(1)),
            "gradient_accumulation_steps": 1,
            "zero_optimization": {"stage": 0},
        },
        "throughput": float(rec["value"]),
    }


def load_measured_priors(runs_dir=".bench_runs"):
    """Collect priors from every trustworthy record under ``runs_dir``
    (top-level ``*.json`` ladder legs + ``sweeps/*.json``)."""
    priors = []
    for path in sorted(glob.glob(os.path.join(runs_dir, "*.json")) +
                       glob.glob(os.path.join(runs_dir, "sweeps",
                                              "*.json"))):
        try:
            with open(path) as f:
                text = f.read().strip()
            if not text:
                continue
            rec = json.loads(text.splitlines()[-1])
        except (OSError, ValueError):
            continue
        p = record_to_prior(rec)
        if p is not None:
            priors.append(p)
    if priors:
        logger.info(f"autotuning: loaded {len(priors)} measured priors "
                    f"from {runs_dir}")
    return priors
