"""Measured-ground-truth priors for the model-based tuner.

Reference ``autotuning/tuner/model_based_tuner.py:19`` starts its cost
model cold — every tuning session re-measures points a previous on-chip
sweep already paid for.  Here trustworthy records from ``.bench_runs/``
(the ladder/sweep artifacts ``tools/bench_retry.sh`` +
``tools/onchip_sweeps.sh`` write, summarized by ``tools/fold_sweeps.py``)
seed ``ModelBasedTuner``'s regression, so TPU tuning starts from measured
ground truth and its FIRST proposal is the best measured config.
"""

import glob
import json
import os
import re

from ..utils.logging import logger

# Trust gate for recorded bench lines — the single source of truth shared
# with bench.py's _untrustworthy: a partial or fallback measurement must
# never be cited, folded, or become a tuning prior.
UNTRUSTED_MARKERS = ("partial", "warmup-estimate", "timing-implausible",
                     "backend=cpu", "cpu-fallback")


def untrustworthy(rec):
    """Why a recorded bench line must not be trusted, or None if it is a
    full, plausible measurement."""
    u = rec.get("unit", "")
    for m in UNTRUSTED_MARKERS:
        if m in u:
            return m
    return None


def _trusted(rec):
    return untrustworthy(rec) is None


def record_to_prior(rec):
    """One bench JSON record → {"ds_config": ..., "throughput": ...} or
    None.  The device bench encodes its config in the unit string
    (``B=<mbs> S=<seq> …``); stage/gas follow the bench's fixed config."""
    if not isinstance(rec, dict) or "metric" in rec and \
            not str(rec.get("metric", "")).startswith("llama_train"):
        return None
    if not _trusted(rec):
        return None
    m = re.search(r"\bB=(\d+)\b", rec.get("unit", ""))
    if m is None or not rec.get("value"):
        return None
    return {
        "ds_config": {
            "train_micro_batch_size_per_gpu": int(m.group(1)),
            "gradient_accumulation_steps": 1,
            "zero_optimization": {"stage": 0},
        },
        "throughput": float(rec["value"]),
    }


# ---------------------------------------------------------- priors files
# ``tools/fold_sweeps.py --priors OUT.json`` exports the aggregated
# (direction, bucket_mb, wire_dtype) bests from ds_bench --overlap archives
# under this schema tag; the autotuner ingests the file to seed its search
# (candidates matching the measured bests are proposed first).
PRIORS_SCHEMA = "ds_tpu_autotune_priors/1"


def load_priors_file(path):
    """Load a ``fold_sweeps --priors`` artifact.  Loud on a missing file or
    wrong schema — a stale/foreign JSON must not silently order the
    search."""
    with open(path) as f:
        data = json.load(f)
    schema = data.get("schema") if isinstance(data, dict) else None
    if schema != PRIORS_SCHEMA:
        raise ValueError(
            f"{path}: not an autotuner priors file (schema {schema!r}, "
            f"expected {PRIORS_SCHEMA!r}; generate one with "
            "tools/fold_sweeps.py --priors OUT.json)")
    if not isinstance(data.get("overlap"), list):
        raise ValueError(f"{path}: priors file has no 'overlap' aggregate "
                         "list")
    return data


def _block_matches_prior(co, best):
    """How many of the measured-best (direction, bucket_mb, wire) choices a
    candidate's comm block agrees with."""
    ov = (co.get("overlap") or {})
    pf = (ov.get("prefetch") or {})
    score = 0
    r = best.get("reduce")
    if r is not None and ov.get("enabled") and \
            float(ov.get("bucket_mb") or -1) == float(r["bucket_mb"]):
        score += 1
    g = best.get("gather")
    if g is not None and pf.get("enabled") and \
            float(pf.get("bucket_mb") or -1) == float(g["bucket_mb"]):
        score += 1
    if r is not None:
        wire = (co.get("wire_dtype", "int8")
                if co.get("enabled") and co.get("quantized_gradients")
                else "fp32")
        if wire == r.get("wire_dtype"):
            score += 1
    return score


def seed_exps_with_priors(exps, priors):
    """Stable-reorder candidate experiments so configs consistent with the
    priors' per-direction bests run first — the grid tuner's early
    stopping and the model-based tuner's cold phase both start from the
    measured ground truth instead of list order."""
    best = {}
    for row in priors.get("overlap", []):
        # fold_sweeps sorts best-first within each direction
        best.setdefault(row.get("direction"), row)
    if not best:
        return list(exps)
    return sorted(
        exps,
        key=lambda e: -_block_matches_prior(
            e["ds_config"].get("comm_optimizations") or {}, best))


def load_measured_priors(runs_dir=".bench_runs"):
    """Collect priors from every trustworthy record under ``runs_dir``
    (top-level ``*.json`` ladder legs + ``sweeps/*.json``)."""
    priors = []
    for path in sorted(glob.glob(os.path.join(runs_dir, "*.json")) +
                       glob.glob(os.path.join(runs_dir, "sweeps",
                                              "*.json"))):
        try:
            with open(path) as f:
                text = f.read().strip()
            if not text:
                continue
            rec = json.loads(text.splitlines()[-1])
        except (OSError, ValueError):
            continue
        p = record_to_prior(rec)
        if p is not None:
            priors.append(p)
    if priors:
        logger.info(f"autotuning: loaded {len(priors)} measured priors "
                    f"from {runs_dir}")
    return priors
