"""Autotuner orchestrator (reference ``autotuning/autotuner.py:42``).

The reference forks ``deepspeed`` launcher jobs per experiment and scrapes
timer logs; here each experiment is an **in-process trial**: build an engine
with the candidate config, run a few profiled steps on the user's data, read
the per-step timings.  (A single SPMD process drives all chips on TPU, so
in-process trials measure the real thing — there is no per-rank subprocess
to orchestrate.)

Two tuning surfaces share the machinery:

* the **legacy grid** (reference ``tune()``): ZeRO stage × micro-batch
  (× mesh factorization), maximizing throughput;
* the **closed comm loop** (``autotuning.tune_comm``, ISSUE 12): a
  topology-probe stage (``probe.py`` — (inter, intra) factorization plus
  per-(op, message-size, wire) median-latency micro-probes reusing the
  in-process ``ds_bench`` candidate machinery), then a search over the
  real ``comm_optimizations``/ZeRO knob surface — per-message-size wire
  dtype (the EQuARX lesson, emitted as a ``wire_dtype_by_size`` ladder),
  hierarchy on/off, ``min_message_size``, ``overlap.bucket_mb`` /
  ``max_inflight`` in both directions, ZeRO stage — scored by measured
  median step time with ``exposed_comm_frac`` as the tie-breaker, then an
  emit stage writing ``autotuning_results/`` with per-trial
  ``ds_bench``-schema rows plus a ready-to-paste config block that is
  round-tripped through the pydantic config models as a self-check before
  it is written.
"""

import itertools
import json
import os
import time

import numpy as np

from .. import telemetry as _telemetry
from ..utils.logging import logger
from .config import MIN_METRICS, AutotuningConfig
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner

TUNERS = {"gridsearch": GridSearchTuner, "random": RandomTuner,
          "model_based": ModelBasedTuner}


class AutotuningError(RuntimeError):
    """A tuning-stage invariant failed (emit self-check, empty space)."""


class Autotuner:

    def __init__(self, model, base_config, model_parameters=None,
                 batch_fn=None, autotuning_config=None, steps_per_trial=None):
        """``model``/``model_parameters``: as for ``initialize()``;
        ``batch_fn(mbs) -> tuple``: builds one global batch for a candidate
        micro-batch size (the data the trials train on)."""
        self.model = model
        self.model_parameters = model_parameters
        self.base_config = dict(base_config)
        at = autotuning_config or self.base_config.get("autotuning", {})
        if not isinstance(at, AutotuningConfig):
            at = AutotuningConfig(**at)
        self.cfg = at
        self.batch_fn = batch_fn
        self.steps_per_trial = steps_per_trial or at.end_profile_step
        self.results = []
        self.model_info = None
        self.topology = None
        self.probe_rows = None
        self.wire_ladders = {}

    # ------------------------------------------------------------ profiling
    def profile_model_info(self):
        """Reference ``_get_model_info`` / profile run (:663)."""
        import jax
        if self.model_parameters is not None:
            n = sum(int(np.prod(x.shape)) for x in
                    jax.tree_util.tree_leaves(self.model_parameters))
        else:
            n = 0
        self.model_info = {"num_params": n}
        return self.model_info

    # ------------------------------------------------------------- probing
    def probe(self):
        """Topology-probe stage (closed comm loop step 1): read the fabric
        factorization and run the per-(op, size, wire) micro-probes, then
        derive the measured wire ladders (``probe.derive_wire_ladder``).
        Idempotent — the search stage calls it lazily."""
        if self.probe_rows is not None:
            return self.probe_rows
        from . import probe as P
        import deepspeed_tpu
        deepspeed_tpu.comm.init_distributed()
        c = self.cfg
        intra = int((self.base_config.get("comm_optimizations") or {})
                    .get("intra_node_size", 0) or 0)
        with _telemetry.span("autotune/probe", cat="autotune"):
            self.topology = P.probe_topology(axis=c.comm_axis,
                                             intra_node_size=intra)
            self.probe_rows = P.run_probes(
                sizes_log2=c.probe_sizes, wires=c.probe_wires,
                axis=c.comm_axis, iters=c.probe_iters,
                warmup=c.probe_warmup, repeat=c.probe_repeat, intra=intra)
        for op in ("reduce_scatter", "all_gather"):
            ladder = P.derive_wire_ladder(self.probe_rows, op=op)
            if ladder is not None:
                self.wire_ladders[op] = ladder
        logger.info(
            f"autotuning probe: topology={self.topology['hierarchy']} "
            f"{len(self.probe_rows)} probe rows, "
            f"ladders={list(self.wire_ladders)}")
        return self.probe_rows

    # --------------------------------------------------------- tuning space
    def _micro_batch_candidates(self):
        lo = max(1, self.cfg.min_train_micro_batch_size_per_gpu)
        hi = max(lo, self.cfg.max_train_micro_batch_size_per_gpu)
        cands = []
        v = lo
        while v <= hi:
            cands.append(v)
            v *= 2
        k = self.cfg.num_tuning_micro_batch_sizes
        if len(cands) > k:
            idx = np.linspace(0, len(cands) - 1, k).round().astype(int)
            cands = [cands[i] for i in idx]
        return cands

    def _mesh_candidates(self):
        """Mesh factorizations to explore (the reference tunes these only by
        re-launching whole jobs; in-process SPMD can rebuild the mesh per
        trial).  Default: dp-only plus 2-way tp and sp splits when the
        device count allows."""
        if self.cfg.mesh_candidates is not None:
            return self.cfg.mesh_candidates
        if not self.cfg.tune_mesh:
            return [None]
        import jax
        n = len(jax.devices())
        cands = [{"dp": -1}]
        if n % 2 == 0 and n > 1:
            cands.append({"dp": -1, "tp": 2})
            cands.append({"dp": -1, "sp": 2})
        if n % 4 == 0 and n > 2:
            cands.append({"dp": -1, "tp": 4})
            cands.append({"dp": -1, "tp": 2, "sp": 2})
        return cands

    def _base_trial_config(self):
        ds = dict(self.base_config)
        ds.pop("autotuning", None)
        return json.loads(json.dumps(ds))  # deep copy

    def build_tuning_space(self):
        """Legacy grid: ZeRO-stage × mbs (× mesh) (reference
        config_templates per stage; mesh is the TPU extension)."""
        stages = self.cfg.zero_stages
        if stages is None:
            stages = [0, 1, 2, 3]
        if self.cfg.fast:
            stages = stages[:2]
        exps = []
        for stage, mbs, mesh in itertools.product(
                stages, self._micro_batch_candidates(),
                self._mesh_candidates()):
            ds = self._base_trial_config()
            ds.setdefault("zero_optimization", {})["stage"] = stage
            ds["train_micro_batch_size_per_gpu"] = mbs
            ds.pop("train_batch_size", None)
            name = f"z{stage}_mbs{mbs}"
            if mesh is not None:
                ds["mesh"] = dict(mesh)
                name += "_" + "x".join(f"{k}{v}" for k, v in mesh.items())
            exps.append({"name": name, "ds_config": ds})
        return exps

    # ---------------------------------------------- comm-loop tuning space
    def _comm_blocks(self, stage=0):
        """Candidate ``comm_optimizations`` blocks (closed comm loop step 2).

        None = the hand-written default (absent block) — ALWAYS in the
        space, so the search can conclude "leave it alone" and the smoke
        gate's "autotuned ≤ default" holds by construction.  The quantized
        candidates sweep each probe wire globally plus the measured
        per-size ladder; the overlap dimension composes bucket_mb ×
        max_inflight onto every base block (overlap has its own gate, so
        it also rides the flat default)."""
        c = self.cfg
        bases = [None]
        ladder_rs = self.wire_ladders.get("reduce_scatter")
        ladder_ag = self.wire_ladders.get("all_gather")
        for hier in (c.hierarchical_candidates or [True]):
            for mms in (c.min_message_sizes or [0]):
                proto = {"enabled": True, "hierarchical_allreduce": hier,
                         "min_message_size": mms}
                for w in c.probe_wires:
                    bases.append(dict(proto, quantized_gradients=True,
                                      wire_dtype=w))
                    # qwZ trial surface (ISSUE-15 satellite): the weight
                    # all-gather wire is its own knob — a config can win
                    # on qwZ alone (stage-3 gather traffic) where qgZ
                    # loses, and vice versa.  qwZ only exists at stage ≥ 3
                    # (the engine gates it there) — below that the
                    # candidate would time the identical non-quantized
                    # program and burn trial budget on a duplicate.
                    if stage >= 3:
                        bases.append(dict(proto, quantized_weights=True,
                                          wire_dtype=w))
                    for gs in (c.group_size_candidates or []):
                        # quantization_group_size candidates: the
                        # error/overhead trade both quantized paths share
                        bases.append(dict(proto, quantized_gradients=True,
                                          wire_dtype=w,
                                          quantization_group_size=gs))
                        if stage >= 3:
                            bases.append(dict(proto, quantized_weights=True,
                                              wire_dtype=w,
                                              quantization_group_size=gs))
                    if "flat_manual" in (c.zero_mode_candidates or []):
                        # the zero-mode dimension (ds_bench --zero-mode's
                        # search twin): race the legacy full-manual qgZ
                        # micro against the GSPMD-first islands default
                        bases.append(dict(proto, quantized_gradients=True,
                                          wire_dtype=w,
                                          zero_mode="flat_manual"))
                if ladder_rs:
                    # the EQuARX candidate: per-size wire choice from the
                    # measured reduce_scatter (qgZ) probes
                    bases.append(dict(proto, quantized_gradients=True,
                                      wire_dtype_by_size=ladder_rs))
                if ladder_ag and stage >= 3:
                    # qwZ sibling: the all_gather probes' ladder carried by
                    # the weight-gather path (one ladder field serves the
                    # whole block, so the two ladders ride separate
                    # candidates; like the per-wire qwZ bases, stage ≥ 3
                    # only — below that qwZ never engages)
                    bases.append(dict(proto, quantized_weights=True,
                                      wire_dtype_by_size=ladder_ag))
        blocks = []
        for b in bases:
            blocks.append(b)
            for mb in c.bucket_mb_candidates:
                for infl in c.max_inflight_candidates:
                    nb = dict(b) if b else {}
                    nb["overlap"] = {"enabled": True, "bucket_mb": mb,
                                     "max_inflight": infl}
                    blocks.append(nb)
        if stage >= 3:
            # forward param-gather prefetch only exists at stage 3; give the
            # gather-direction priors (and sweep bests) candidates to land
            # on — one set over the flat base, one over the qwZ ladder base
            pf_bases = [None] + ([bases[-1]] if ladder_ag else [])
            for b in pf_bases:
                for mb in c.bucket_mb_candidates:
                    for infl in c.max_inflight_candidates:
                        nb = dict(b) if b else {}
                        nb["overlap"] = {"prefetch": {
                            "enabled": True, "bucket_mb": mb,
                            "max_inflight": infl}}
                        blocks.append(nb)
        return blocks

    @staticmethod
    def _block_name(stage, block):
        if block is None:
            return f"z{stage}_default"
        parts = [f"z{stage}"]
        if block.get("enabled"):
            if block.get("wire_dtype_by_size"):
                parts.append("ladder")
            elif block.get("quantized_gradients"):
                parts.append(f"w{block.get('wire_dtype', 'int8')}")
            elif block.get("quantized_weights"):
                # qwZ-only base: the wire must be in the name or every
                # probe wire would collide on "qw"
                parts.append(f"qw{block.get('wire_dtype', 'int8')}")
            if block.get("quantized_weights") and (
                    block.get("quantized_gradients")
                    or block.get("wire_dtype_by_size")):
                parts.append("qw")
            if block.get("quantization_group_size"):
                parts.append(f"gs{block['quantization_group_size']}")
            if block.get("zero_mode") == "flat_manual":
                parts.append("fm")
            if block.get("hierarchical_allreduce"):
                parts.append("hier")
            if block.get("min_message_size"):
                parts.append(f"mms{block['min_message_size']}")
        ov = block.get("overlap") or {}
        if ov.get("enabled"):
            parts.append(f"ov{ov['bucket_mb']:g}x{ov.get('max_inflight', 2)}")
        pf = ov.get("prefetch") or {}
        if pf.get("enabled"):
            parts.append(f"pf{pf['bucket_mb']:g}x{pf.get('max_inflight', 2)}")
        return "_".join(parts)

    def build_comm_space(self):
        """Candidate full configs for the comm loop: comm block × ZeRO
        stage, micro-batch and mesh pinned to the base config (the comm
        loop tunes the communication surface, not the batch trinity)."""
        self.probe()
        stages = self.cfg.zero_stages
        if stages is None:
            stages = [int((self.base_config.get("zero_optimization") or {})
                          .get("stage", 0))]
        user_co = self.base_config.get("comm_optimizations")
        exps = []
        for stage in stages:
            stage_exps = []
            for block in self._comm_blocks(stage):
                ds = self._base_trial_config()
                ds.setdefault("zero_optimization", {})["stage"] = stage
                if block is None:
                    ds.pop("comm_optimizations", None)
                else:
                    ds["comm_optimizations"] = json.loads(json.dumps(block))
                stage_exps.append({"name": self._block_name(stage, block),
                                   "ds_config": ds,
                                   "pinned": block is None})
            if user_co is not None:
                # the user's own hand-written block IS a candidate (pinned
                # right after the absent-block default): "leave it alone"
                # must mean keeping what the user had, and the ≤-baseline
                # comparison must cover it, not just the bare default
                ds = self._base_trial_config()
                ds.setdefault("zero_optimization", {})["stage"] = stage
                ds["comm_optimizations"] = json.loads(json.dumps(user_co))
                stage_exps.insert(1, {"name": f"z{stage}_user",
                                      "ds_config": ds, "pinned": True})
            moe_user = self.base_config.get("moe") or {}
            if moe_user.get("enabled"):
                # MoE dispatch-wire candidates: expert dispatch is the
                # hardest collective in the stack — when the model runs
                # MoE, sweep the quantized-dispatch wire next to the comm
                # blocks (docs/moe.md).  The user's own moe block rides
                # every other candidate unchanged; these vary ONLY the
                # dispatch wire — and the wire the base config ALREADY
                # runs is skipped (a byte-identical duplicate would burn
                # one measured trial per stage under a budget).
                base_wire = (moe_user.get("wire_dtype", "int8")
                             if moe_user.get("quantized_dispatch")
                             else None)
                for w in list(self.cfg.probe_wires) + ["fp32"]:
                    if w == base_wire:
                        continue
                    ds = self._base_trial_config()
                    ds.setdefault("zero_optimization", {})["stage"] = stage
                    ds["moe"] = dict(json.loads(json.dumps(moe_user)),
                                     quantized_dispatch=True, wire_dtype=w)
                    stage_exps.append({"name": f"z{stage}_moed_{w}",
                                       "ds_config": ds})
            exps.extend(stage_exps)
        if not exps:
            raise AutotuningError("comm tuning space is empty — check "
                                  "zero_stages / candidate lists")
        if self.cfg.priors_file:
            from .priors import load_priors_file, seed_exps_with_priors
            priors = load_priors_file(self.cfg.priors_file)
            # the baseline candidates (absent-block default + the user's
            # own block) stay pinned at the FRONT: they are what the
            # acceptance compares against, and a priors ordering that
            # pushed them past the trial budget would break the
            # "autotuned ≤ default" invariant (and the smoke gate)
            pinned = [e for e in exps if e.get("pinned")]
            rest = [e for e in exps if not e.get("pinned")]
            exps = pinned + seed_exps_with_priors(rest, priors)
            logger.info(f"autotuning: search seeded from priors file "
                        f"{self.cfg.priors_file}")
        return exps

    # ---------------------------------------------- memory-feasibility filter
    def memory_feasibility_filter(self, exps):
        """Drop candidates whose STATIC model-state estimate already
        exceeds per-chip device memory — a trial that is guaranteed to OOM
        is a wasted slot in the budget (``profiling/mem_estimator``, the
        reference ``estimate_zero*_model_states_mem_needs`` put to work).
        Pinned candidates (the hand-written default, the user's own block)
        are NEVER dropped: they anchor the ≤-default acceptance even when
        the filter thinks they are doomed — in that case it warns and lets
        the measured trial deliver the verdict.  No-op when the model size
        or the memory limit is unknown (CPU smoke boxes report host RAM,
        which tiny models never exceed)."""
        n = (self.model_info or {}).get("num_params", 0)
        try:
            from ..accelerator import get_accelerator
            total = get_accelerator().total_memory()
        except Exception:
            total = 0
        if not n or not total:
            return exps
        from ..profiling.mem_estimator import estimate_zero_states
        import jax
        world = max(1, len(jax.devices()))
        kept, dropped = [], []
        for exp in exps:
            ds = exp.get("ds_config") or {}
            stage = int((ds.get("zero_optimization") or {}).get("stage", 0))
            mesh = ds.get("mesh") or {}
            model_par = 1
            for ax in ("tp", "sp", "pp"):
                model_par *= max(1, int(mesh.get(ax, 1) or 1))
            ep = max(1, int(mesh.get("ep", 1) or 1))
            dp = max(1, world // (model_par * ep))
            cb = 2 if ((ds.get("fp16") or {}).get("enabled")
                       or (ds.get("bfloat16") or {}).get("enabled")
                       or (ds.get("bf16") or {}).get("enabled")) else 4
            # model parallelism divides the resident dense states too
            est = estimate_zero_states(
                n, stage, dp, ep=ep,
                compute_dtype=cb)["total_bytes"] / model_par
            if est > total and not exp.get("pinned"):
                dropped.append((exp["name"], est))
                continue
            if est > total:
                logger.warning(
                    "autotuning: pinned candidate %s statically needs "
                    "%.2f GiB of %.2f GiB HBM — kept (it anchors the "
                    "baseline) but expect the trial to OOM",
                    exp["name"], est / 2**30, total / 2**30)
            kept.append(exp)
        if dropped:
            logger.warning(
                "autotuning: memory-feasibility filter rejected %d of %d "
                "candidates before trials (model states exceed %.2f GiB "
                "per chip): %s", len(dropped), len(exps), total / 2**30,
                ", ".join(f"{name} ({est / 2**30:.2f} GiB)"
                          for name, est in dropped[:8])
                + (" …" if len(dropped) > 8 else ""))
        if not kept and exps:
            # never hand the tuner an empty space: keep the first
            # candidate (highest-stage spaces shard the most — the legacy
            # grid orders by stage) and let the measured trial decide
            logger.warning(
                "autotuning: every candidate failed the memory-"
                "feasibility estimate — keeping %s so the search can "
                "still report a measured verdict", exps[0]["name"])
            kept = [exps[0]]
        return kept

    # ----------------------------------------------------------- experiment
    def _run_experiment(self, exp):
        import jax
        import deepspeed_tpu
        from ..comm.comm import comms_logger
        from ..utils import groups
        ds = exp["ds_config"]
        mbs = ds.get("train_micro_batch_size_per_gpu", 1)
        groups.reset_mesh()
        deepspeed_tpu.comm.destroy_process_group()
        c = _telemetry.counter("autotune/trials",
                               help="autotuner trials run")
        if c is not None:
            c.inc()
        prev_log = (comms_logger.enabled, comms_logger.prof_all,
                    comms_logger.sync_timing)
        # trials are hermetic: the surrounding session's accumulated comm
        # stats come back after the trial, not an empty table
        prev_dict = comms_logger.comms_dict
        # ... and so does the MoE dispatcher: each trial engine's bring-up
        # reconfigures the module-global dispatch options (incl. the
        # z*_moed_* wire candidates) — the LAST trial's choice must not
        # silently steer the session's expert dispatch afterwards
        from ..moe import engine as _moe_engine
        prev_moe = _moe_engine.snapshot()
        try:
            with _telemetry.span(f"autotune/trial/{exp['name']}",
                                 cat="autotune"):
                engine, _, _, _ = deepspeed_tpu.initialize(
                    model=self.model, model_parameters=self.model_parameters,
                    config=ds)
                batch = self.batch_fn(mbs * engine.dp_world_size)
                if not isinstance(batch, tuple):
                    batch = (batch, )
                if engine.params is None:
                    # flax module without explicit params: born-sharded init
                    engine.initialize_parameters(0, *batch)
                warmup = max(1, self.cfg.start_profile_step - 1)
                steps = max(self.steps_per_trial, warmup + 1)
                # eager-collective latency during the measured window — the
                # exposed_comm_frac tie-breaker (jit-internal collectives
                # are already hidden by XLA and don't appear here).
                # sync_timing: without it, timed_op records async ENQUEUE
                # latency (microseconds regardless of payload) and the
                # tie-breaker would be scheduler noise; the fence cost is
                # identical across candidates, so the comparison stays fair
                comms_logger.enabled = True
                comms_logger.prof_all = True
                comms_logger.sync_timing = True
                comms_logger.comms_dict = {}
                step_times = []
                comm_s = 0.0
                for i in range(steps):
                    if i == warmup:
                        comms_logger.comms_dict = {}
                    t0 = time.perf_counter()
                    loss = engine(*batch)
                    engine.backward(loss)
                    engine.step()
                    # per-step fence: median-of-steps needs real step
                    # boundaries (identical protocol for every candidate)
                    jax.block_until_ready(
                        jax.tree_util.tree_leaves(engine.params)[0])
                    if i >= warmup:
                        step_times.append(time.perf_counter() - t0)
                for sizes in comms_logger.comms_dict.values():
                    for (_, latencies, *_rest) in sizes.values():
                        comm_s += sum(latencies)
                measured = len(step_times)
                total = sum(step_times)
                step_med = float(np.median(step_times))
                samples = mbs * engine.dp_world_size * \
                    engine.gradient_accumulation_steps() * measured
                thr = samples / total if total > 0 else 0.0
                result = {
                    "throughput": thr,
                    "latency": total / measured,
                    "step_time_ms": step_med * 1e3,
                    "step_time": step_med * 1e3,
                    "exposed_comm_frac": (min(1.0, comm_s / total)
                                          if total > 0 else 0.0),
                    "flops": None,
                    "steps": measured,
                }
        except Exception as e:  # OOM / invalid combo → prune the point
            logger.warning(f"autotuning exp {exp['name']} failed: {e}")
            result = None
        finally:
            (comms_logger.enabled, comms_logger.prof_all,
             comms_logger.sync_timing) = prev_log
            comms_logger.comms_dict = prev_dict
            _moe_engine.restore(prev_moe)
            groups.reset_mesh()
            deepspeed_tpu.comm.destroy_process_group()
        self.results.append({"name": exp["name"], "result": result,
                             "ds_config": exp["ds_config"]})
        return result

    # ---------------------------------------------------------------- tune
    def tune(self):
        self.profile_model_info()
        c = self.cfg
        if c.tune_comm:
            exps = self.build_comm_space()
            metric = "step_time" if c.metric == "throughput" else c.metric
            mode = "min" if metric in MIN_METRICS else "max"
            tie = "exposed_comm_frac"
        else:
            exps = self.build_tuning_space()
            metric, tie = c.metric, None
            mode = "min" if metric in MIN_METRICS else "max"
        exps = self.memory_feasibility_filter(exps)
        tuner_cls = TUNERS.get(c.tuner_type, GridSearchTuner)
        kw = {}
        if tuner_cls is ModelBasedTuner:
            kw["priors"] = self._measured_priors(metric)
        if tie is not None:
            kw["tie_breaker"] = tie
            kw["tie_rtol"] = c.tie_rtol
        tuner = tuner_cls(exps, self._run_experiment, metric=metric,
                          mode=mode, **kw)
        with _telemetry.span("autotune/search", cat="autotune"):
            best = tuner.tune(sample_size=1,
                              n_trials=c.tuner_num_trials,
                              early_stopping=c.tuner_early_stopping)
        if best is not None:
            g = _telemetry.gauge("autotune/best_" + metric,
                                 help="autotuner best primary metric")
            if g is not None:
                g.set(float(best["result"][metric]))
        self._write_results(best, metric)
        return best

    def _measured_priors(self, metric):
        if not (self.cfg.priors_path and
                os.path.isdir(self.cfg.priors_path)):
            return None
        if metric != "throughput":
            # bench records are tokens/s (a throughput); seeding a
            # latency/step-time search with them would silently run cold
            logger.warning(
                f"measured priors only exist for metric='throughput' "
                f"(configured: {metric!r}); tuning starts cold")
            return None
        from .priors import load_measured_priors
        return load_measured_priors(self.cfg.priors_path)

    # ---------------------------------------------------------------- emit
    def _trial_rows(self, metric):
        """Per-trial rows in the uniform ``ds_bench --json`` schema
        (``benchmarks.comm_bench.bench_row`` — the one row constructor all
        producers share), so the trial archive folds/plots with the probe
        and sweep archives."""
        from ..benchmarks.comm_bench import bench_row
        rows = []
        for r in self.results:
            res = r["result"]
            co = (r.get("ds_config") or {}).get("comm_optimizations") or {}
            ov = co.get("overlap") or {}
            rows.append(bench_row(
                op="trial",
                trial=r["name"],
                latency_us=(res["step_time_ms"] * 1e3 if res else None),
                repeat=res["steps"] if res else 0,
                wire_dtype=("ladder" if co.get("wire_dtype_by_size") else
                            co.get("wire_dtype", "int8")
                            if (co.get("quantized_gradients")
                                or co.get("quantized_weights"))
                            else "fp32"),
                bucket_mb=(float(ov["bucket_mb"])
                           if ov.get("enabled") else None),
                exposed_comm_frac=(res.get("exposed_comm_frac")
                                   if res else None),
                metric=metric,
                metric_value=res.get(metric) if res else None,
            ))
        return rows

    @staticmethod
    def _check_round_trip(section, src, model):
        """Emit self-check: every key we are about to publish must survive
        the pydantic round-trip with an equal value — a field the model
        clamps, coerces, or drops would otherwise emit a block that
        configures something other than what was measured.  Keys are read
        back through the model's field/alias map (``stage3_*`` alias
        spellings are how the docs write the zero block — an alias is a
        rename the model itself honors, not drift)."""
        from pydantic import BaseModel
        fields = type(model).model_fields
        alias_to_name = {f.alias: name for name, f in fields.items()
                         if f.alias}
        for k, v in src.items():
            attr = alias_to_name.get(k, k)
            got = getattr(model, attr, None)
            if isinstance(v, dict) and isinstance(got, BaseModel):
                Autotuner._check_round_trip(f"{section}.{k}", v, got)
            elif got != v:
                raise AutotuningError(
                    f"emitted config failed round-trip self-check: "
                    f"{section}.{k} = {v!r} came back as {got!r}")

    def emit_block(self, best):
        """The ready-to-paste ``comm_optimizations`` + ``zero_optimization``
        block of the winning trial, round-tripped through the pydantic
        config models as a self-check before anyone writes it."""
        ds = best["ds_config"]
        block = {}
        co = ds.get("comm_optimizations")
        if co is not None:
            block["comm_optimizations"] = json.loads(json.dumps(co))
        zo = ds.get("zero_optimization")
        if zo:
            block["zero_optimization"] = json.loads(json.dumps(zo))
        from ..runtime.config import CommOptimizationsConfig
        from ..runtime.zero.config import DeepSpeedZeroConfig
        if "comm_optimizations" in block:
            self._check_round_trip(
                "comm_optimizations", block["comm_optimizations"],
                CommOptimizationsConfig(**block["comm_optimizations"]))
        if "zero_optimization" in block:
            self._check_round_trip(
                "zero_optimization", block["zero_optimization"],
                DeepSpeedZeroConfig(**block["zero_optimization"]))
        return block

    def _write_results(self, best, metric="throughput"):
        os.makedirs(self.cfg.results_dir, exist_ok=True)

        def _dump(name, payload):
            with open(os.path.join(self.cfg.results_dir, name), "w") as f:
                json.dump(payload, f, indent=2)

        _dump("exps.json", self.results)
        _dump("model_info.json", self.model_info)
        _dump("trials.json", {"metric": metric,
                              "rows": self._trial_rows(metric)})
        if self.topology is not None:
            _dump("topology.json", self.topology)
        if self.probe_rows is not None:
            _dump("probes.json", {"rows": self.probe_rows,
                                  "wire_ladders": self.wire_ladders})
        if best is not None:
            _dump("ds_config_optimal.json", best["ds_config"])
            _dump("tuned_block.json", self.emit_block(best))
            logger.info(
                f"autotuning best: {best['name']} "
                f"{metric}={best['result'][metric]:.3f}")


def run_autotuning(args=None, model=None, base_config=None,
                   model_parameters=None, batch_fn=None,
                   steps_per_trial=None):
    """THE autotuning entry (launcher ``--autotuning`` and programmatic).

    * programmatic: pass ``model``/``model_parameters``/``batch_fn`` and a
      ``base_config`` carrying an ``autotuning`` block (the
      ``deepspeed.initialize``-style config — ``autotuning.enabled: false``
      means this function refuses to run, matching "off by default = zero
      behavior change");
    * launcher (``deepspeed --autotuning run script.py --deepspeed_config
      cfg.json``): the config is read from the user args and the trials run
      on a built-in synthetic model — the comm surface is model-agnostic
      enough for a first config, and the emitted block documents exactly
      what was measured.

    Returns the best experiment dict (or None when every trial failed).
    """
    if base_config is None and args is not None:
        cfg_path = None
        user_args = list(getattr(args, "user_args", []) or [])
        for i, a in enumerate(user_args):
            if a == "--deepspeed_config" and i + 1 < len(user_args):
                cfg_path = user_args[i + 1]
            elif a.startswith("--deepspeed_config="):
                cfg_path = a.split("=", 1)[1]
        if cfg_path is None:
            raise AutotuningError(
                "--autotuning needs --deepspeed_config <json> among the "
                "user args (the config whose autotuning block drives the "
                "search)")
        with open(cfg_path) as f:
            base_config = json.load(f)
    base_config = dict(base_config or {})
    at = base_config.get("autotuning", {})
    at_cfg = at if isinstance(at, AutotuningConfig) else \
        AutotuningConfig(**at)
    if not at_cfg.enabled:
        raise AutotuningError(
            "autotuning.enabled is false — set it to true to run the "
            "search (off by default = zero behavior change)")
    if model is None:
        model, model_parameters, batch_fn = _synthetic_trial_model()
        base_config.setdefault("train_micro_batch_size_per_gpu", 4)
        base_config.setdefault("optimizer",
                               {"type": "sgd", "params": {"lr": 0.1}})
    tuner = Autotuner(model, base_config, model_parameters=model_parameters,
                      batch_fn=batch_fn, autotuning_config=at_cfg,
                      steps_per_trial=steps_per_trial)
    return tuner.tune()


def _synthetic_trial_model(hidden=64, nlayers=4, seed=0):
    """Tiny deterministic MLP + batch builder for model-less entries (the
    launcher path and tools/autotune_smoke.py): enough layers/leaves that
    the overlap partitioners form >1 bucket and the grad reduce is real."""
    rng = np.random.default_rng(seed)
    params = {}
    for i in range(nlayers):
        params[f"layer_{i}"] = {
            "w": (rng.standard_normal((hidden, hidden)) * 0.2
                  ).astype("float32"),
            "b": np.zeros((hidden, ), "float32"),
        }

    def apply_fn(p, x, y):
        import jax.numpy as jnp
        h = x
        for i in range(nlayers):
            h = jnp.tanh(h @ p[f"layer_{i}"]["w"] + p[f"layer_{i}"]["b"])
        return jnp.mean((h - y) ** 2)

    def batch_fn(global_batch):
        r = np.random.default_rng(1)
        x = r.standard_normal((global_batch, hidden)).astype("float32")
        return (x, np.tanh(x * 0.5).astype("float32"))

    return apply_fn, params, batch_fn
