"""Autotuner orchestrator (reference ``autotuning/autotuner.py:42``).

The reference forks ``deepspeed`` launcher jobs per experiment and scrapes
timer logs; here each experiment is an **in-process trial**: build an engine
with the candidate config, run a few profiled steps on the user's data, read
the throughput timer.  (A single SPMD process drives all chips on TPU, so
in-process trials measure the real thing — there is no per-rank subprocess to
orchestrate.)

Flow (mirrors reference ``tune()``):
  1. model-info profile (num params / per-step memory estimate, :663);
  2. build the tuning space: ZeRO stages × micro-batch candidates (:741);
  3. run the tuner strategy (grid/random/model-based) with early stopping;
  4. write ``autotuning_results/`` with per-exp metrics + the best config.
"""

import itertools
import json
import os
import time

import numpy as np

from ..utils.logging import logger
from .config import AutotuningConfig
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner

TUNERS = {"gridsearch": GridSearchTuner, "random": RandomTuner,
          "model_based": ModelBasedTuner}


class Autotuner:

    def __init__(self, model, base_config, model_parameters=None,
                 batch_fn=None, autotuning_config=None, steps_per_trial=None):
        """``model``/``model_parameters``: as for ``initialize()``;
        ``batch_fn(mbs) -> tuple``: builds one global batch for a candidate
        micro-batch size (the data the trials train on)."""
        self.model = model
        self.model_parameters = model_parameters
        self.base_config = dict(base_config)
        at = autotuning_config or self.base_config.get("autotuning", {})
        if not isinstance(at, AutotuningConfig):
            at = AutotuningConfig(**at)
        self.cfg = at
        self.batch_fn = batch_fn
        self.steps_per_trial = steps_per_trial or at.end_profile_step
        self.results = []
        self.model_info = None

    # ------------------------------------------------------------ profiling
    def profile_model_info(self):
        """Reference ``_get_model_info`` / profile run (:663)."""
        import jax
        if self.model_parameters is not None:
            n = sum(int(np.prod(x.shape)) for x in
                    jax.tree_util.tree_leaves(self.model_parameters))
        else:
            n = 0
        self.model_info = {"num_params": n}
        return self.model_info

    # --------------------------------------------------------- tuning space
    def _micro_batch_candidates(self):
        lo = max(1, self.cfg.min_train_micro_batch_size_per_gpu)
        hi = max(lo, self.cfg.max_train_micro_batch_size_per_gpu)
        cands = []
        v = lo
        while v <= hi:
            cands.append(v)
            v *= 2
        k = self.cfg.num_tuning_micro_batch_sizes
        if len(cands) > k:
            idx = np.linspace(0, len(cands) - 1, k).round().astype(int)
            cands = [cands[i] for i in idx]
        return cands

    def _mesh_candidates(self):
        """Mesh factorizations to explore (the reference tunes these only by
        re-launching whole jobs; in-process SPMD can rebuild the mesh per
        trial).  Default: dp-only plus 2-way tp and sp splits when the
        device count allows."""
        if self.cfg.mesh_candidates is not None:
            return self.cfg.mesh_candidates
        if not self.cfg.tune_mesh:
            return [None]
        import jax
        n = len(jax.devices())
        cands = [{"dp": -1}]
        if n % 2 == 0 and n > 1:
            cands.append({"dp": -1, "tp": 2})
            cands.append({"dp": -1, "sp": 2})
        if n % 4 == 0 and n > 2:
            cands.append({"dp": -1, "tp": 4})
            cands.append({"dp": -1, "tp": 2, "sp": 2})
        return cands

    def build_tuning_space(self):
        """ZeRO-stage × mbs (× mesh) grid (reference config_templates per
        stage; mesh is the TPU extension)."""
        stages = self.cfg.zero_stages
        if stages is None:
            stages = [0, 1, 2, 3]
        if self.cfg.fast:
            stages = stages[:2]
        exps = []
        for stage, mbs, mesh in itertools.product(
                stages, self._micro_batch_candidates(),
                self._mesh_candidates()):
            ds = dict(self.base_config)
            ds.pop("autotuning", None)
            ds = json.loads(json.dumps(ds))  # deep copy
            ds.setdefault("zero_optimization", {})["stage"] = stage
            ds["train_micro_batch_size_per_gpu"] = mbs
            ds.pop("train_batch_size", None)
            name = f"z{stage}_mbs{mbs}"
            if mesh is not None:
                ds["mesh"] = dict(mesh)
                name += "_" + "x".join(f"{k}{v}" for k, v in mesh.items())
            exps.append({"name": name, "ds_config": ds})
        return exps

    # ----------------------------------------------------------- experiment
    def _run_experiment(self, exp):
        import jax
        import deepspeed_tpu
        from ..utils import groups
        ds = exp["ds_config"]
        mbs = ds["train_micro_batch_size_per_gpu"]
        groups.reset_mesh()
        deepspeed_tpu.comm.destroy_process_group()
        try:
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model, model_parameters=self.model_parameters,
                config=ds)
            batch = self.batch_fn(mbs * engine.dp_world_size)
            if not isinstance(batch, tuple):
                batch = (batch, )
            if engine.params is None:
                # flax module without explicit parameters: born-sharded init
                engine.initialize_parameters(0, *batch)
            warmup = max(1, self.cfg.start_profile_step - 1)
            steps = max(self.steps_per_trial, warmup + 1)
            t0 = None
            for i in range(steps):
                loss = engine(*batch)
                engine.backward(loss)
                engine.step()
                if i + 1 == warmup:
                    jax.block_until_ready(loss)
                    t0 = time.perf_counter()
            jax.block_until_ready(
                jax.tree_util.tree_leaves(engine.params)[0])
            dt = time.perf_counter() - t0
            measured = steps - warmup
            samples = mbs * engine.dp_world_size * \
                engine.gradient_accumulation_steps() * measured
            thr = samples / dt if dt > 0 else 0.0
            result = {"throughput": thr, "latency": dt / measured,
                      "flops": None, "steps": measured}
        except Exception as e:  # OOM / invalid combo → prune the point
            logger.warning(f"autotuning exp {exp['name']} failed: {e}")
            result = None
        finally:
            groups.reset_mesh()
            deepspeed_tpu.comm.destroy_process_group()
        self.results.append({"name": exp["name"], "result": result})
        return result

    # ---------------------------------------------------------------- tune
    def tune(self):
        self.profile_model_info()
        exps = self.build_tuning_space()
        tuner_cls = TUNERS.get(self.cfg.tuner_type, GridSearchTuner)
        kw = {}
        if tuner_cls is ModelBasedTuner and self.cfg.priors_path and \
                os.path.isdir(self.cfg.priors_path):
            if self.cfg.metric != "throughput":
                # bench records are tokens/s (a throughput); seeding a
                # latency/flops search with them would silently run cold
                logger.warning(
                    f"measured priors only exist for metric='throughput' "
                    f"(configured: {self.cfg.metric!r}); tuning starts "
                    "cold")
            else:
                from .priors import load_measured_priors
                kw["priors"] = load_measured_priors(self.cfg.priors_path)
        tuner = tuner_cls(exps, self._run_experiment, metric=self.cfg.metric,
                          **kw)
        best = tuner.tune(sample_size=1,
                          n_trials=self.cfg.tuner_num_trials,
                          early_stopping=self.cfg.tuner_early_stopping)
        self._write_results(best)
        return best

    def _write_results(self, best):
        os.makedirs(self.cfg.results_dir, exist_ok=True)
        with open(os.path.join(self.cfg.results_dir, "exps.json"), "w") as f:
            json.dump(self.results, f, indent=2)
        with open(os.path.join(self.cfg.results_dir,
                               "model_info.json"), "w") as f:
            json.dump(self.model_info, f, indent=2)
        if best is not None:
            with open(os.path.join(self.cfg.results_dir,
                                   "ds_config_optimal.json"), "w") as f:
                json.dump(best["ds_config"], f, indent=2)
            logger.info(f"autotuning best: {best['name']} "
                        f"{self.cfg.metric}={best['result'][self.cfg.metric]:.1f}")
