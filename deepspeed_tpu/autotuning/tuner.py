"""Tuner strategies (reference ``autotuning/tuner/``): grid / random /
model-based search over experiment lists.  The reference's XGBoost cost
model becomes a ridge-regression-on-features model (no xgboost dependency;
the feature space is small — batch/ZeRO knobs plus the comm surface).

Two extensions over the reference:

* ``mode`` — "max" (throughput-like metrics) or "min" (latency /
  step_time): the comm autotuner minimizes measured step time.
* ``tie_breaker`` — a secondary result key (the comm loop uses
  ``exposed_comm_frac``): when two candidates land within ``tie_rtol``
  relative distance on the primary metric, the lower tie-breaker wins —
  between two configs with indistinguishable step time, prefer the one
  that hides more communication (it degrades more gracefully when the
  real model's compute/comm ratio shifts).  Without a tie_breaker the
  comparison is the reference's strict better-than.
"""

import random as _random

import numpy as np

#: payload bits per element of each wire format — the cost model's view of
#: "how aggressive is this config's quantization"
WIRE_BITS = {"fp32": 32, "fp12": 12, "int8": 8, "fp8": 8, "fp6": 6,
             "int4": 4}


class BaseTuner:
    """Reference ``tuner/base_tuner.py:13``: iterate experiments, track best."""

    def __init__(self, exps, runner, metric="throughput", mode="max",
                 tie_breaker=None, tie_rtol=0.02):
        if mode not in ("max", "min"):
            raise ValueError(f"tuner mode {mode!r} must be 'max' or 'min'")
        self.all_exps = list(exps)
        self.runner = runner
        self.metric = metric
        self.mode = mode
        self.tie_breaker = tie_breaker
        self.tie_rtol = tie_rtol
        self.best_exp = None
        self.best_metric_val = None
        self.best_tie_val = None

    def has_next(self):
        return len(self.all_exps) > 0

    def next_batch(self, sample_size=1):
        raise NotImplementedError

    def _beats_best(self, val, tie):
        if self.best_metric_val is None:
            return True
        sign = 1.0 if self.mode == "max" else -1.0
        gain = (val - self.best_metric_val) * sign
        if self.tie_breaker is None:
            return gain > 0
        margin = abs(self.best_metric_val) * self.tie_rtol
        if gain > margin:
            return True
        if gain >= -margin and tie is not None and \
                self.best_tie_val is not None and tie < self.best_tie_val:
            return True          # statistical tie: lower tie-breaker wins
        return False

    def update(self, exps, results):
        sign = 1.0 if self.mode == "max" else -1.0
        for exp, res in zip(exps, results):
            val = None if res is None else res.get(self.metric)
            exp["result"] = res
            if val is None:
                continue
            tie = res.get(self.tie_breaker) if self.tie_breaker else None
            if self._beats_best(val, tie):
                self.best_tie_val = tie
                self.best_exp = exp
            # the margin anchor stays pinned to the extreme primary value
            # ever measured — NOT the tie-broken winner's value.  Otherwise
            # chained within-margin ties would ratchet the baseline
            # arbitrarily far from the true best, and the returned config
            # could exceed tie_rtol of the measured minimum.
            if self.best_metric_val is None or \
                    (val - self.best_metric_val) * sign > 0:
                self.best_metric_val = val

    def tune(self, sample_size=1, n_trials=1000, early_stopping=None):
        trials, since_best = 0, 0
        while self.has_next() and trials < n_trials:
            batch = self.next_batch(sample_size)
            results = [self.runner(exp) for exp in batch]
            prev_best = self.best_exp
            self.update(batch, results)
            trials += len(batch)
            since_best = 0 if self.best_exp is not prev_best else \
                since_best + len(batch)
            if early_stopping and since_best >= early_stopping:
                break
        return self.best_exp


class GridSearchTuner(BaseTuner):
    """Reference ``index_based_tuner.py:27``: in-order exhaustive."""

    def next_batch(self, sample_size=1):
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class RandomTuner(BaseTuner):
    """Reference ``index_based_tuner.py:11``: uniform without replacement."""

    def next_batch(self, sample_size=1):
        k = min(sample_size, len(self.all_exps))
        batch = _random.sample(self.all_exps, k)
        for b in batch:
            self.all_exps.remove(b)
        return batch


def featurize_config(cfg):
    """Numeric feature vector of a candidate ``ds_config`` — the batch/ZeRO
    trinity the reference models plus the comm_optimizations surface the
    closed loop searches (wire aggressiveness, hierarchy, size floor,
    overlap bucketing in both directions)."""
    z = cfg.get("zero_optimization", {}).get("stage", 0)
    mbs = cfg.get("train_micro_batch_size_per_gpu", 1)
    gas = cfg.get("gradient_accumulation_steps", 1)
    co = cfg.get("comm_optimizations") or {}
    ov = co.get("overlap") or {}
    pf = ov.get("prefetch") or {}
    ladder = co.get("wire_dtype_by_size")
    quantizing = bool(co.get("enabled")) and (
        co.get("quantized_gradients") or co.get("quantized_weights"))
    if not quantizing:
        wire_bits = 32.0
    elif ladder:
        # one rung-parsing implementation — the same normalization the
        # engine dispatches on (loud on malformed rungs)
        from ..comm.collectives import build_wire_ladder
        rungs = build_wire_ladder(ladder) or ()
        bits = [WIRE_BITS.get(w, 32) for _, w in rungs]
        wire_bits = float(np.mean(bits)) if bits else 32.0
    else:
        wire_bits = float(WIRE_BITS.get(co.get("wire_dtype", "int8"), 32))
    return [
        float(z),
        float(np.log2(max(mbs, 1))),
        float(gas),
        1.0 if co.get("enabled") else 0.0,
        1.0 if co.get("hierarchical_allreduce") else 0.0,
        wire_bits,
        float(np.log2(1.0 + co.get("min_message_size", 0))),
        1.0 if ov.get("enabled") else 0.0,
        float(np.log2(1.0 + (ov.get("bucket_mb") or 0.0))),
        float(ov.get("max_inflight", 0) if ov.get("enabled") else 0),
        1.0 if pf.get("enabled") else 0.0,
        float(np.log2(1.0 + (pf.get("bucket_mb") or 0.0))),
    ]


class ModelBasedTuner(BaseTuner):
    """Reference ``model_based_tuner.py:19``: fit a cost model on measured
    points, propose the predicted-best next.

    ``priors``: measured ground-truth points
    (``[{"ds_config": ..., "<metric>": value}]``, see
    ``autotuning/priors.load_measured_priors``) — with ≥3 priors the FIRST
    proposal is already the predicted-best config instead of a cold guess.
    Priors steer the proposal order only until enough LIVE trials exist
    (their units are the bench's tokens/s for a fixed model; live trials
    measure the user's model in samples/s — mixing both in one fit would
    let the priors' magnitude drown the live signal)."""

    _MIN_FIT = 3

    def __init__(self, exps, runner, metric="throughput", mode="max",
                 tie_breaker=None, tie_rtol=0.02, tuning_space=None,
                 priors=None):
        super().__init__(exps, runner, metric, mode=mode,
                         tie_breaker=tie_breaker, tie_rtol=tie_rtol)
        self._X, self._y = [], []            # live measurements only
        self._pX, self._py = [], []          # measured priors
        for p in priors or []:
            val = p.get(metric)
            if val is None or "ds_config" not in p:
                continue
            self._pX.append(self._featurize(p))
            self._py.append(float(val))

    def _featurize(self, exp):
        return featurize_config(exp["ds_config"])

    def _predict(self, exp):
        # live measurements take over as soon as there are enough to fit;
        # until then, measured priors (if any) order the proposals
        if len(self._y) >= self._MIN_FIT:
            A, y = self._X, self._y
        elif len(self._py) >= self._MIN_FIT:
            A, y = self._pX, self._py
        else:
            return 0.0
        A = np.array(A)
        y = np.array(y)
        # ridge regression on a degree-2 feature expansion
        def expand(M):
            return np.concatenate([M, M**2, np.ones((len(M), 1))], axis=1)
        Ae, Xe = expand(A), expand(np.array([self._featurize(exp)]))
        w = np.linalg.solve(Ae.T @ Ae + 1e-3 * np.eye(Ae.shape[1]), Ae.T @ y)
        return float((Xe @ w)[0])

    def next_batch(self, sample_size=1):
        ranked = sorted(self.all_exps, key=self._predict,
                        reverse=(self.mode == "max"))
        batch = ranked[:sample_size]
        for b in batch:
            self.all_exps.remove(b)
        return batch

    def update(self, exps, results):
        super().update(exps, results)
        for exp, res in zip(exps, results):
            if res is not None and res.get(self.metric) is not None:
                self._X.append(self._featurize(exp))
                self._y.append(res[self.metric])
