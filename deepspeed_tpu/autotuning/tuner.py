"""Tuner strategies (reference ``autotuning/tuner/``): grid / random /
model-based search over experiment lists.  The reference's XGBoost cost model
becomes a ridge-regression-on-features model (no xgboost dependency; the
feature space is tiny — stage, mbs, gas)."""

import random as _random

import numpy as np


class BaseTuner:
    """Reference ``tuner/base_tuner.py:13``: iterate experiments, track best."""

    def __init__(self, exps, runner, metric="throughput"):
        self.all_exps = list(exps)
        self.runner = runner
        self.metric = metric
        self.best_exp = None
        self.best_metric_val = None

    def has_next(self):
        return len(self.all_exps) > 0

    def next_batch(self, sample_size=1):
        raise NotImplementedError

    def update(self, exps, results):
        for exp, res in zip(exps, results):
            val = None if res is None else res.get(self.metric)
            exp["result"] = res
            if val is not None and (self.best_metric_val is None or
                                    val > self.best_metric_val):
                self.best_metric_val = val
                self.best_exp = exp

    def tune(self, sample_size=1, n_trials=1000, early_stopping=None):
        trials, since_best = 0, 0
        while self.has_next() and trials < n_trials:
            batch = self.next_batch(sample_size)
            results = [self.runner(exp) for exp in batch]
            prev_best = self.best_metric_val
            self.update(batch, results)
            trials += len(batch)
            since_best = 0 if self.best_metric_val != prev_best else \
                since_best + len(batch)
            if early_stopping and since_best >= early_stopping:
                break
        return self.best_exp


class GridSearchTuner(BaseTuner):
    """Reference ``index_based_tuner.py:27``: in-order exhaustive."""

    def next_batch(self, sample_size=1):
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class RandomTuner(BaseTuner):
    """Reference ``index_based_tuner.py:11``: uniform without replacement."""

    def next_batch(self, sample_size=1):
        k = min(sample_size, len(self.all_exps))
        batch = _random.sample(self.all_exps, k)
        for b in batch:
            self.all_exps.remove(b)
        return batch


class ModelBasedTuner(BaseTuner):
    """Reference ``model_based_tuner.py:19``: fit a cost model on measured
    points, propose the predicted-best next.

    ``priors``: measured ground-truth points
    (``[{"ds_config": ..., "<metric>": value}]``, see
    ``autotuning/priors.load_measured_priors``) — with ≥3 priors the FIRST
    proposal is already the predicted-best config instead of a cold guess.
    Priors steer the proposal order only until enough LIVE trials exist
    (their units are the bench's tokens/s for a fixed model; live trials
    measure the user's model in samples/s — mixing both in one fit would
    let the priors' magnitude drown the live signal)."""

    _MIN_FIT = 3

    def __init__(self, exps, runner, metric="throughput", tuning_space=None,
                 priors=None):
        super().__init__(exps, runner, metric)
        self._X, self._y = [], []            # live measurements only
        self._pX, self._py = [], []          # measured priors
        for p in priors or []:
            val = p.get(metric)
            if val is None or "ds_config" not in p:
                continue
            self._pX.append(self._featurize(p))
            self._py.append(float(val))

    def _featurize(self, exp):
        cfg = exp["ds_config"]
        z = cfg.get("zero_optimization", {}).get("stage", 0)
        mbs = cfg.get("train_micro_batch_size_per_gpu", 1)
        gas = cfg.get("gradient_accumulation_steps", 1)
        return [float(z), float(np.log2(max(mbs, 1))), float(gas)]

    def _predict(self, exp):
        # live measurements take over as soon as there are enough to fit;
        # until then, measured priors (if any) order the proposals
        if len(self._y) >= self._MIN_FIT:
            A, y = self._X, self._y
        elif len(self._py) >= self._MIN_FIT:
            A, y = self._pX, self._py
        else:
            return 0.0
        A = np.array(A)
        y = np.array(y)
        # ridge regression on a degree-2 feature expansion
        def expand(M):
            return np.concatenate([M, M**2, np.ones((len(M), 1))], axis=1)
        Ae, Xe = expand(A), expand(np.array([self._featurize(exp)]))
        w = np.linalg.solve(Ae.T @ Ae + 1e-3 * np.eye(Ae.shape[1]), Ae.T @ y)
        return float((Xe @ w)[0])

    def next_batch(self, sample_size=1):
        ranked = sorted(self.all_exps, key=self._predict, reverse=True)
        batch = ranked[:sample_size]
        for b in batch:
            self.all_exps.remove(b)
        return batch

    def update(self, exps, results):
        super().update(exps, results)
        for exp, res in zip(exps, results):
            if res is not None and res.get(self.metric) is not None:
                self._X.append(self._featurize(exp))
                self._y.append(res[self.metric])
