"""Topology-probe stage of the closed-loop comm autotuner.

Answers two questions before any config trial runs:

1. **What is the fabric?**  :func:`probe_topology` reads the
   ``comm/collectives/topology.py`` (inter-node, intra-node) factorization
   of the comm axis — the same :func:`factor_group` the engine's
   hierarchical variants dispatch on, so the probe sees exactly the
   hierarchy the tuned config would use.
2. **What does each (op, message-size, wire) actually cost here?**
   :func:`run_probes` races the flat fp32 collective against each
   candidate quantized wire format per size bucket, using the in-process
   ``ds_bench`` candidate machinery (``benchmarks.comm_bench.probe_op``)
   with warmup + repeated timed blocks + median/IQR — no subprocess
   orchestration, no single-shot noise.

:func:`derive_wire_ladder` then applies the EQuARX lesson (arxiv
2506.17615: the optimal quantization choice varies by message size and
op): per size bucket, the measured-fastest wire wins, and adjacent
same-wire buckets merge into a ``wire_dtype_by_size`` ladder the
collectives engine dispatches on (``comm/collectives/engine.py``).
"""

from ..utils.logging import logger

#: logical probe surface → (flat op, quantized op) in ds_bench vocabulary.
#: reduce_scatter feeds the gradient (qgZ) wire choice, all_gather the
#: weight (qwZ) one.
PROBE_OPS = {
    "reduce_scatter": ("reduce_scatter", "quant_reduce_scatter"),
    "all_gather": ("all_gather", "quant_all_gather"),
}


def probe_topology(axis="dp", mesh=None, intra_node_size=0):
    """Factorize the comm axis into (inter, intra) — the hierarchy the
    tuned config's ``hierarchical_allreduce`` / 2-hop variants would ride.
    Returns a JSON-able report; ``hierarchy`` is None on flat fabrics
    (single node, indivisible split)."""
    from ..comm.backend import ProcessGroup
    from ..comm.collectives.topology import factor_group
    from ..utils import groups
    if mesh is None:
        mesh = groups.get_mesh_state().mesh
    report = {
        "axis": axis,
        "world": int(mesh.shape.get(axis, 1)),
        "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
        "hierarchy": None,
    }
    if report["world"] > 1:
        h = factor_group(ProcessGroup(mesh, (axis, )),
                         intra_node_size=intra_node_size)
        if h is not None:
            report["hierarchy"] = {
                "outer_axes": list(h.outer_axes),
                "inner_axes": list(h.inner_axes),
                "inter": int(h.outer_size),
                "intra": int(h.inner_size),
            }
    return report


def run_probes(ops=("reduce_scatter", "all_gather"),
               sizes_log2=(14, 18, 22), wires=("int8", "fp8"), axis="dp",
               mesh=None, iters=4, warmup=1, repeat=3, intra=0,
               group_size=None, print_fn=None):
    """Per-(op, message-size-bucket, wire) latency/bandwidth probes.

    For every logical op and size bucket, measures the flat fp32 op plus
    each quantized wire candidate; every row is the uniform ``ds_bench``
    JSON schema (median ``latency_us``, ``iqr_us``, ``repeat``) tagged
    with ``probe_op`` (the logical op) and ``size_log2`` (the bucket).
    """
    from ..benchmarks.comm_bench import GROUP_SIZE, probe_op
    gs = group_size or GROUP_SIZE
    rows = []
    for logical in ops:
        if logical not in PROBE_OPS:
            raise ValueError(f"unknown probe op {logical!r} "
                             f"(have {', '.join(PROBE_OPS)})")
        flat_op, quant_op = PROBE_OPS[logical]
        for p in sizes_log2:
            nbytes = 1 << int(p)
            candidates = [("fp32", flat_op)] + [(w, quant_op) for w in wires]
            for wire, bench_op in candidates:
                row = probe_op(
                    bench_op, nbytes, axis=axis, mesh=mesh, iters=iters,
                    warmup=warmup, repeat=repeat, intra=intra,
                    wire=(wire if wire != "fp32" else "int8"),
                    group_size=gs)
                row["probe_op"] = logical
                row["wire_dtype"] = wire
                row["size_log2"] = int(p)
                rows.append(row)
                if print_fn is not None:
                    print_fn(f"# probe {logical:<16} 2^{p:<3} {wire:<6} "
                             f"median={row['latency_us']:9.1f}us "
                             f"iqr={row['iqr_us']:7.1f}us")
    return rows


def derive_wire_ladder(rows, op="reduce_scatter"):
    """Measured probe rows → ``wire_dtype_by_size`` ladder for ``op``.

    Per size bucket the wire with the lowest median latency wins;
    contiguous same-wire buckets merge into one rung whose ``max_bytes``
    is the largest probed size of the run, and the last run becomes the
    catch-all (``max_bytes: null``).  Returns None when no rows cover
    ``op`` (the caller skips the ladder candidate)."""
    per_size = {}
    for r in rows:
        if r.get("probe_op") != op or r.get("latency_us") is None:
            continue
        p = int(r["size_log2"])
        cur = per_size.get(p)
        if cur is None or r["latency_us"] < cur["latency_us"]:
            per_size[p] = r
    if not per_size:
        return None
    ladder = []
    for p in sorted(per_size):
        wire = per_size[p]["wire_dtype"]
        if ladder and ladder[-1][1] == wire:
            ladder[-1][0] = 1 << p       # extend the same-wire run
        else:
            ladder.append([1 << p, wire])
    ladder[-1][0] = None                 # largest run = catch-all rung
    logger.info(f"autotuning: derived {op} wire ladder {ladder}")
    return ladder
