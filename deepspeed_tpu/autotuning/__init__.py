"""Autotuning (reference ``deepspeed/autotuning/``): explores ZeRO stage ×
micro-batch-size (× offload) spaces, measures throughput, emits the best
config."""

from .autotuner import Autotuner
from .config import AutotuningConfig
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner
