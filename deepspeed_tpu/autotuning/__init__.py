"""Autotuning (reference ``deepspeed/autotuning/``): the legacy ZeRO-stage ×
micro-batch grid plus the closed-loop comm/ZeRO autotuner (topology probe →
measured search over wire dtypes / hierarchy / overlap bucketing → emitted
``comm_optimizations`` + ``zero_optimization`` block; docs/autotuning.md)."""

from .autotuner import Autotuner, AutotuningError, run_autotuning
from .config import AutotuningConfig
from .probe import derive_wire_ladder, probe_topology, run_probes
from .tuner import (GridSearchTuner, ModelBasedTuner, RandomTuner,
                    featurize_config)
