"""Autotuning config — same JSON keys as reference
``autotuning/constants.py`` / ``autotuning/config.py``."""

from typing import Dict, List, Optional

from ..runtime.config_utils import DeepSpeedConfigModel


class AutotuningConfig(DeepSpeedConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = True
    start_profile_step: int = 3
    end_profile_step: int = 5
    metric: str = "throughput"          # throughput | latency | flops
    tuner_type: str = "gridsearch"      # gridsearch | random | model_based
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    arg_mappings: Optional[Dict[str, str]] = None
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    max_train_micro_batch_size_per_gpu: int = 1024
    min_train_micro_batch_size_per_gpu: int = 1
    num_tuning_micro_batch_sizes: int = 3
    mp_size: int = 1
    model_info: Optional[Dict] = None
    zero_stages: Optional[List[int]] = None  # TPU addition: restrict space
    # TPU addition: also explore mesh factorizations (the launcher-level
    # knob the reference cannot tune in-process).  Candidates are dicts for
    # the config's "mesh" key, e.g. [{"dp": -1}, {"dp": -1, "tp": 2}];
    # None + tune_mesh=True → derived from the device count.
    tune_mesh: bool = False
    mesh_candidates: Optional[List[Dict]] = None
    # TPU addition: seed ModelBasedTuner with measured on-chip records from
    # this directory (tools/bench_retry.sh artifacts).  Opt-in ("" = off):
    # stale artifacts in a launch cwd must not silently bias a search.
    priors_path: str = ""
