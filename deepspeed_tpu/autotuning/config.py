"""Autotuning config — same JSON keys as reference
``autotuning/constants.py`` / ``autotuning/config.py`` for the surviving
surface, plus the comm-surface closed loop (ISSUE 12 / docs/autotuning.md).

Unlike every other config block, this one REJECTS unknown keys
(``extra="forbid"``): a mistyped search knob (``bucket_mb_candiates``)
would otherwise silently tune the default space and burn the whole trial
budget measuring nothing the user asked for.  Stale reference-only fields
(``arg_mappings``, ``mp_size``, ``model_info``, ``overwrite``,
``max/min_train_batch_size``) that were parsed-but-ignored are gone for
the same reason — configs carrying them now fail loudly instead of
pretending the knob did something.
"""

from typing import Dict, List, Optional

from pydantic import ConfigDict, model_validator

from ..runtime.config_utils import DeepSpeedConfigModel

METRICS = ("throughput", "latency", "flops", "step_time")
TUNER_TYPES = ("gridsearch", "random", "model_based")
#: metrics where smaller is better (the tuner runs in min mode)
MIN_METRICS = ("latency", "step_time")


class AutotuningConfig(DeepSpeedConfigModel):
    # pydantic v2 merges this with DeepSpeedConfigModel's ConfigDict, so
    # only the one divergence is stated: unknown keys fail loudly (see
    # module doc) instead of the base's extra="allow"
    model_config = ConfigDict(extra="forbid")

    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    start_profile_step: int = 3
    end_profile_step: int = 5
    # throughput | latency | flops | step_time (step_time/latency = min mode)
    metric: str = "throughput"
    tuner_type: str = "gridsearch"      # gridsearch | random | model_based
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    max_train_micro_batch_size_per_gpu: int = 1024
    min_train_micro_batch_size_per_gpu: int = 1
    num_tuning_micro_batch_sizes: int = 3
    zero_stages: Optional[List[int]] = None  # TPU addition: restrict space
    # TPU addition: also explore mesh factorizations (the launcher-level
    # knob the reference cannot tune in-process).  Candidates are dicts for
    # the config's "mesh" key, e.g. [{"dp": -1}, {"dp": -1, "tp": 2}];
    # None + tune_mesh=True → derived from the device count.
    tune_mesh: bool = False
    mesh_candidates: Optional[List[Dict]] = None
    # TPU addition: seed ModelBasedTuner with measured on-chip records from
    # this directory (tools/bench_retry.sh artifacts).  Opt-in ("" = off):
    # stale artifacts in a launch cwd must not silently bias a search.
    priors_path: str = ""

    # ------------------------------------------------ comm-surface loop
    # tune_comm: walk the comm_optimizations/ZeRO surface instead of the
    # legacy stage × micro-batch grid — topology probe first, then the
    # search stage over per-size wire dtype / hierarchy / min_message_size
    # / overlap bucketing, scored by measured step time with
    # exposed_comm_frac as the tie-breaker (docs/autotuning.md).
    tune_comm: bool = False
    # fold_sweeps --priors artifact; "" = cold start.  Candidates matching
    # the measured-best (direction, bucket_mb, wire) aggregates are
    # proposed first.
    priors_file: str = ""
    # mesh axis the comm trials/probes sweep
    comm_axis: str = "dp"
    # micro-probe surface: log2 payload bytes per size bucket, quantized
    # wire formats to race against the flat fp32 op, and the warmup +
    # repeat-block protocol (median + IQR, see ds_bench --repeat)
    probe_sizes: List[int] = [14, 18, 22]
    probe_wires: List[str] = ["int8", "fp8"]
    probe_iters: int = 4
    probe_warmup: int = 1
    probe_repeat: int = 3
    # search-space candidate lists
    bucket_mb_candidates: List[float] = [1.0, 4.0, 32.0]
    max_inflight_candidates: List[int] = [2]
    min_message_sizes: List[int] = [0]
    hierarchical_candidates: List[bool] = [True]
    # quantization_group_size candidates composed onto the quantized
    # (qgZ/qwZ) wire bases; empty (default) keeps the block default —
    # the space is unchanged unless the user opts into the sweep
    group_size_candidates: List[int] = []
    # the zero-mode search dimension (ds_bench --zero-mode's twin): when
    # "flat_manual" is listed, every quantized-gradient wire base gets a
    # legacy full-manual-micro sibling so the measured trial decides which
    # micro architecture carries qgZ on THIS model/mesh (docs/zero.md)
    zero_mode_candidates: List[str] = ["gspmd", "flat_manual"]
    # candidates within this relative step-time margin count as a tie and
    # are broken by the lower exposed_comm_frac
    tie_rtol: float = 0.02

    @model_validator(mode="after")
    def _check_enums(self):
        if self.metric not in METRICS:
            raise ValueError(f"autotuning.metric {self.metric!r} unknown "
                             f"(have {', '.join(METRICS)})")
        if self.tuner_type not in TUNER_TYPES:
            raise ValueError(
                f"autotuning.tuner_type {self.tuner_type!r} unknown "
                f"(have {', '.join(TUNER_TYPES)})")
        from ..comm.collectives import WIRE_FORMATS
        for w in self.probe_wires:
            if w not in WIRE_FORMATS:
                raise ValueError(
                    f"autotuning.probe_wires entry {w!r} unknown "
                    f"(have {', '.join(WIRE_FORMATS)})")
        from ..runtime.zero.gspmd import ZERO_MODES
        for zm in self.zero_mode_candidates:
            if zm not in ZERO_MODES:
                raise ValueError(
                    f"autotuning.zero_mode_candidates entry {zm!r} unknown "
                    f"(have {', '.join(ZERO_MODES)})")
        for gs in self.group_size_candidates:
            if int(gs) < 128:
                raise ValueError(
                    "autotuning.group_size_candidates entries must be "
                    f">= 128 (got {gs}) — the codecs lane-align scale "
                    "groups down to 128")
        if self.start_profile_step < 1:
            raise ValueError("autotuning.start_profile_step must be >= 1")
        return self
