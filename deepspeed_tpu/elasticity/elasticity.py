"""Elastic batch-size scheduling — reference ``elasticity/elasticity.py``.

The contract (reference ``compute_elastic_config`` :233): from an elasticity
config block, produce a final train batch size that is simultaneously
divisible into (micro_batch × grad_accum × world_size) for EVERY admissible
chip count, so the job can lose or gain hosts and resume from checkpoint
without changing the effective batch (loss-curve-stable elasticity).

v0.1 (:83): batch = highly-composite multiple of some micro-batch candidate;
v0.2 (:126): adds fixed micro-batch per chip-count and model-parallel /
chips-per-node divisibility constraints.
"""

import numpy as np

from ..utils.logging import logger
from . import constants as C


class ElasticityError(Exception):
    """Base elasticity error (reference elasticity/config.py)."""


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


# Candidate multipliers: highly-composite numbers — many divisors → many
# admissible chip counts (reference HCN_LIST)
HCN_LIST = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
            1260, 1680, 2520, 5040]


def _candidate_batch_sizes(base_list, max_acceptable_batch_size):
    candidates = set()
    for base in base_list:
        for hcn in HCN_LIST:
            if base * hcn <= max_acceptable_batch_size:
                candidates.add(base * hcn)
    return sorted(candidates)


def get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """All chip counts g for which batch_size = mbs × gas × g works for some
    admissible micro batch (reference ``_get_valid_gpus``)."""
    valid = set()
    for mbs in micro_batches:
        if batch_size % mbs != 0:
            continue
        total_micros = batch_size // mbs
        for g in range(1, total_micros + 1):
            if total_micros % g == 0 and min_valid_gpus <= g <= max_valid_gpus:
                valid.add(g)
    return sorted(valid)


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus,
                        max_gpus, prefer_larger):
    max_valid_gpus = 0
    valid_gpus = None
    final_batch_size = int(min(micro_batches))
    for batch_size in candidate_batch_sizes:
        current = get_valid_gpus(batch_size, micro_batches, min_gpus,
                                 max_gpus)
        better = (len(current), batch_size if prefer_larger else -batch_size)
        best = (max_valid_gpus,
                final_batch_size if prefer_larger else -final_batch_size)
        if current and better > best:
            max_valid_gpus = len(current)
            valid_gpus = current
            final_batch_size = batch_size
    return final_batch_size, valid_gpus


def get_compatible_gpus(micro_batches, max_acceptable_batch_size, min_gpus=1,
                        max_gpus=None, prefer_larger=True,
                        num_gpus_per_node=1, model_parallel_size=1,
                        version=0.1):
    """Core solver (reference ``_get_compatible_gpus_v01``/``_v02``)."""
    if version not in (0.1, 0.2):
        raise ElasticityConfigError(f"Unknown elasticity version {version}")
    max_gpus = max_gpus or max_acceptable_batch_size
    micro_batches = sorted(set(int(m) for m in micro_batches))
    if any(m <= 0 for m in micro_batches):
        raise ElasticityConfigError("micro batches must be positive")

    if version == 0.2 and (model_parallel_size > 1 or num_gpus_per_node > 1):
        # batch math runs in DATA-PARALLEL-replica space; min/max_gpus are
        # CHIP bounds, so map them down by mp before solving and filter the
        # final chip counts (= dp × mp) to whole-node multiples
        group = int(np.lcm(num_gpus_per_node, model_parallel_size))
        mp = model_parallel_size
        min_dp = max(1, -(-min_gpus // mp))   # ceil
        max_dp = max(1, max_gpus // mp)
        candidates = _candidate_batch_sizes(micro_batches,
                                            max_acceptable_batch_size)
        batch, dp_counts = get_best_candidates(candidates, micro_batches,
                                               min_dp, max_dp, prefer_larger)
        if dp_counts is None:
            raise ElasticityConfigError(
                f"No valid chip counts for max batch "
                f"{max_acceptable_batch_size} with micros {micro_batches}")
        gpus = [dp * mp for dp in dp_counts
                if (dp * mp) % group == 0 and min_gpus <= dp * mp <= max_gpus]
        if not gpus:
            raise ElasticityConfigError(
                "model-parallel/node constraints eliminated every chip count")
        return batch, gpus

    candidates = _candidate_batch_sizes(micro_batches,
                                        max_acceptable_batch_size)
    batch, gpus = get_best_candidates(candidates, micro_batches, min_gpus,
                                      max_gpus, prefer_larger)
    if gpus is None:
        raise ElasticityConfigError(
            f"No valid chip counts for max batch {max_acceptable_batch_size} "
            f"with micros {micro_batches}")
    return batch, gpus


def _micro_batch_for(final_batch_size, world_size, micro_batches,
                     prefer_larger):
    candidates = [m for m in sorted(micro_batches, reverse=prefer_larger)
                  if final_batch_size % (m * world_size) == 0]
    if not candidates:
        return None
    return candidates[0]


def elasticity_enabled(ds_config: dict):
    return ds_config.get(C.ELASTICITY, {}).get(C.ENABLED, C.ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """Guard against the engine mutating the schedule after launch
    (reference ``elasticity.py`` same name)."""
    import json
    import os
    env = os.environ.get("DEEPSPEED_ELASTICITY_CONFIG")
    if env:
        frozen = json.loads(env)
        if frozen != runtime_elastic_config_dict:
            raise ElasticityConfigError(
                "Elastic config changed between launcher and runtime; "
                "this would break batch-size stability across restarts")
    else:
        os.environ["DEEPSPEED_ELASTICITY_CONFIG"] = json.dumps(
            runtime_elastic_config_dict)


def compute_elastic_config(ds_config: dict, target_deepspeed_version=None,
                           world_size=0, return_microbatch=False):
    """Reference ``elasticity.py:233``.

    Returns ``(final_batch_size, valid_gpus[, micro_batch_size])``; raises
    ``ElasticityIncompatibleWorldSize`` when ``world_size`` is not in the
    admissible set.
    """
    if not elasticity_enabled(ds_config):
        raise ElasticityError("elasticity is not enabled in the config")
    cfg = ds_config[C.ELASTICITY]
    version = float(cfg.get(C.VERSION, C.VERSION_DEFAULT))
    micro_batches = cfg.get(C.MICRO_BATCHES, C.MICRO_BATCHES_DEFAULT)
    max_batch = cfg.get(C.MAX_ACCEPTABLE_BATCH_SIZE,
                        C.MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
    min_gpus = cfg.get(C.MIN_GPUS, C.MIN_GPUS_DEFAULT)
    max_gpus = cfg.get(C.MAX_GPUS, C.MAX_GPUS_DEFAULT)
    prefer_larger = cfg.get(C.PREFER_LARGER_BATCH,
                            C.PREFER_LARGER_BATCH_DEFAULT)
    num_gpus_per_node = cfg.get(C.NUM_GPUS_PER_NODE,
                                C.NUM_GPUS_PER_NODE_DEFAULT)
    mp_size = cfg.get(C.MODEL_PARALLEL_SIZE, C.MODEL_PARALLEL_SIZE_DEFAULT)

    final_batch_size, valid_gpus = get_compatible_gpus(
        micro_batches, max_batch, min_gpus, max_gpus, prefer_larger,
        num_gpus_per_node, mp_size, version)

    if world_size > 0 and world_size not in valid_gpus:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} not in admissible chip counts "
            f"{valid_gpus}")

    logger.info("elasticity: batch=%s admissible chip counts=%s",
                final_batch_size, valid_gpus)
    if return_microbatch:
        ws = world_size if world_size > 0 else valid_gpus[0]
        mbs = _micro_batch_for(final_batch_size, ws // max(mp_size, 1),
                               micro_batches, prefer_larger)
        return final_batch_size, valid_gpus, mbs
    return final_batch_size, valid_gpus
