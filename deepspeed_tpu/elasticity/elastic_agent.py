"""Elastic worker agent — reference ``elasticity/elastic_agent.py:32``
(``DSElasticAgent(LocalElasticAgent)`` atop torchelastic).

TPU analog: there is no torchelastic; the agent is a restart supervisor used
by ``launcher/launch.py --enable_elastic_training``.  On worker failure it
recomputes the admissible-chip-count schedule (``compute_elastic_config``)
against the surviving hosts and relaunches — checkpoint+resume (the
reference's real recovery story, SURVEY.md §5) does the state recovery.
"""

import os
import subprocess
import sys
import time

from ..utils.logging import logger
from .elasticity import (ElasticityIncompatibleWorldSize,
                         compute_elastic_config)


class DSElasticAgent:
    def __init__(self, cmd, env, ds_config, min_nodes=1, max_nodes=None,
                 max_restarts=100, monitor_interval=1.0):
        self.cmd = list(cmd)
        self.env = dict(env)
        self.ds_config = ds_config
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.max_restarts = max_restarts
        self.monitor_interval = monitor_interval
        self.restart_count = 0

    def _validate_world(self, world_size):
        if self.ds_config is None:
            # restart supervision without batch-schedule validation
            # (launch.py has no parsed DS config; checkpoint+resume
            # provides the state recovery either way)
            return True
        try:
            compute_elastic_config(self.ds_config, world_size=world_size)
            return True
        except ElasticityIncompatibleWorldSize:
            return False

    def _elastic_env(self, world_size, coordinator=None):
        """Worker env for the (possibly rescaled) world: rendezvous address
        + recomputed batch schedule exported as DS_ELASTIC_* (the worker's
        ``deepspeed.initialize`` resolves its micro-batch from these like the
        reference reads torchelastic's rendezvous results)."""
        env = dict(self.env)
        env["WORLD_SIZE"] = str(world_size)
        # must track the rescaled world — a stale inherited value would make
        # survivors rendezvous for the OLD process count and hang forever
        env["JAX_PROCESS_COUNT"] = str(world_size)
        if coordinator is not None:
            env["COORDINATOR_ADDRESS"] = coordinator
            env["MASTER_ADDR"], _, port = coordinator.partition(":")
            env["MASTER_PORT"] = port or env.get("MASTER_PORT", "29500")
        if self.ds_config is not None:
            final, _, micro = compute_elastic_config(
                self.ds_config, world_size=world_size, return_microbatch=True)
            env["DS_ELASTIC_TRAIN_BATCH_SIZE"] = str(final)
            env["DS_ELASTIC_MICRO_BATCH_SIZE"] = str(micro)
            env["DS_ELASTIC_WORLD_SIZE"] = str(world_size)
        return env

    def run(self, world_size, rescale=None, coordinator=None):
        """Supervise one local worker; restart on failure up to
        max_restarts as long as the world size stays admissible.

        ``rescale``: optional callback ``(world_size, restart_count) →
        (new_world_size, new_coordinator | None)`` consulted after each
        failure — the TPU-pod rescale story (reference DSElasticAgent's
        torchelastic rendezvous shrink): a dead host's capacity is dropped,
        the batch schedule re-solves for the surviving chip count, and the
        workers restart into a fresh jax.distributed rendezvous, resuming
        from the latest checkpoint.
        """
        while True:
            if not self._validate_world(world_size):
                raise ElasticityIncompatibleWorldSize(
                    f"cannot run with world size {world_size}")
            env = self._elastic_env(world_size, coordinator)
            proc = subprocess.Popen(self.cmd, env=env)
            while proc.poll() is None:
                time.sleep(self.monitor_interval)
            if proc.returncode == 0:
                return 0
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                logger.error("elastic agent: max restarts exceeded")
                return proc.returncode
            if rescale is not None:
                new_world, new_coord = rescale(world_size,
                                               self.restart_count)
                if new_world != world_size:
                    logger.warning(
                        "elastic agent: rescaling world %d → %d",
                        world_size, new_world)
                world_size = new_world
                coordinator = new_coord or coordinator
            logger.warning(
                "elastic agent: worker died rc=%s; restart %d/%d "
                "(world=%d)", proc.returncode, self.restart_count,
                self.max_restarts, world_size)
