"""Elastic worker agent — reference ``elasticity/elastic_agent.py:32``
(``DSElasticAgent(LocalElasticAgent)`` atop torchelastic).

TPU analog: there is no torchelastic; the agent is a restart supervisor used
by ``launcher/launch.py --enable_elastic_training``.  On worker failure it
recomputes the admissible-chip-count schedule (``compute_elastic_config``)
against the surviving hosts and relaunches — checkpoint+resume (the
reference's real recovery story, SURVEY.md §5) does the state recovery.
"""

import os
import subprocess
import sys
import time

from ..utils.logging import logger
from .elasticity import (ElasticityIncompatibleWorldSize,
                         compute_elastic_config)


class DSElasticAgent:
    def __init__(self, cmd, env, ds_config, min_nodes=1, max_nodes=None,
                 max_restarts=100, monitor_interval=1.0):
        self.cmd = list(cmd)
        self.env = dict(env)
        self.ds_config = ds_config
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.max_restarts = max_restarts
        self.monitor_interval = monitor_interval
        self.restart_count = 0

    def _validate_world(self, world_size):
        if self.ds_config is None:
            # restart supervision without batch-schedule validation
            # (launch.py has no parsed DS config; checkpoint+resume
            # provides the state recovery either way)
            return True
        try:
            compute_elastic_config(self.ds_config, world_size=world_size)
            return True
        except ElasticityIncompatibleWorldSize:
            return False

    def run(self, world_size):
        """Supervise one local worker; restart on failure up to
        max_restarts as long as the world size stays admissible."""
        while True:
            if not self._validate_world(world_size):
                raise ElasticityIncompatibleWorldSize(
                    f"cannot run with world size {world_size}")
            proc = subprocess.Popen(self.cmd, env=self.env)
            while proc.poll() is None:
                time.sleep(self.monitor_interval)
            if proc.returncode == 0:
                return 0
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                logger.error("elastic agent: max restarts exceeded")
                return proc.returncode
            logger.warning(
                "elastic agent: worker died rc=%s; restart %d/%d",
                proc.returncode, self.restart_count, self.max_restarts)
