"""Elastic worker agent — reference ``elasticity/elastic_agent.py:32``
(``DSElasticAgent(LocalElasticAgent)`` atop torchelastic).

TPU analog: there is no torchelastic; the agent is a restart supervisor used
by ``launcher/launch.py --enable_elastic_training``.  On worker failure it
recomputes the admissible-chip-count schedule (``compute_elastic_config``)
against the surviving hosts and relaunches — checkpoint+resume (the
reference's real recovery story, SURVEY.md §5) does the state recovery.
"""

import os
import subprocess
import sys
import time

from ..utils.logging import logger
from .elasticity import (ElasticityIncompatibleWorldSize,
                         compute_elastic_config)


#: synthetic "return code" recorded when the watchdog killed a hung worker
STALLED = "stalled"


class DSElasticAgent:
    def __init__(self, cmd, env, ds_config, min_nodes=1, max_nodes=None,
                 max_restarts=100, monitor_interval=1.0,
                 heartbeat_dir=None, stall_timeout=0.0,
                 restart_backoff=1.0, max_restart_backoff=60.0):
        """``stall_timeout`` > 0 arms the heartbeat watchdog: workers beat
        into ``heartbeat_dir`` (exported as ``DS_TPU_HEARTBEAT_DIR``) once
        per step, and a worker silent for longer than the timeout is killed
        and funneled into the same rescale-and-relaunch path a dead worker
        takes — a hung collective no longer wedges the pod forever.
        Restarts back off exponentially (``restart_backoff · 2^k``, capped)
        so a crash-looping cluster doesn't hot-spin."""
        self.cmd = list(cmd)
        self.env = dict(env)
        self.ds_config = ds_config
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.max_restarts = max_restarts
        self.monitor_interval = monitor_interval
        self.restart_count = 0
        self.stall_timeout = float(stall_timeout or 0.0)
        self.restart_backoff = float(restart_backoff)
        self.max_restart_backoff = float(max_restart_backoff)
        self.heartbeat_dir = heartbeat_dir
        if not self.stall_timeout and isinstance(ds_config, dict):
            # a parsed DS config can carry the watchdog block — honor it so
            # the JSON knob works wherever the agent sees the config (under
            # bare launch.py there is no parsed config; use --stall_timeout)
            wd = (ds_config.get("resilience") or {}).get("watchdog") or {}
            if wd.get("enabled"):
                self.stall_timeout = float(wd.get("stall_timeout", 300.0))
                self.heartbeat_dir = (self.heartbeat_dir
                                      or wd.get("heartbeat_dir") or None)
        self._watchdog = None
        if self.stall_timeout > 0:
            from .watchdog import HeartbeatMonitor
            if self.heartbeat_dir is None:
                import tempfile
                self.heartbeat_dir = os.path.join(
                    tempfile.gettempdir(), f"ds_tpu_heartbeat_{os.getpid()}")
            self._watchdog = HeartbeatMonitor(self.heartbeat_dir,
                                              self.stall_timeout)

    def _validate_world(self, world_size):
        if self.ds_config is None:
            # restart supervision without batch-schedule validation
            # (launch.py has no parsed DS config; checkpoint+resume
            # provides the state recovery either way)
            return True
        try:
            compute_elastic_config(self.ds_config, world_size=world_size)
            return True
        except ElasticityIncompatibleWorldSize:
            return False

    def _elastic_env(self, world_size, coordinator=None):
        """Worker env for the (possibly rescaled) world: rendezvous address
        + recomputed batch schedule exported as DS_ELASTIC_* (the worker's
        ``deepspeed.initialize`` resolves its micro-batch from these like the
        reference reads torchelastic's rendezvous results)."""
        env = dict(self.env)
        env["WORLD_SIZE"] = str(world_size)
        # must track the rescaled world — a stale inherited value would make
        # survivors rendezvous for the OLD process count and hang forever
        env["JAX_PROCESS_COUNT"] = str(world_size)
        if coordinator is not None:
            env["COORDINATOR_ADDRESS"] = coordinator
            env["MASTER_ADDR"], _, port = coordinator.partition(":")
            env["MASTER_PORT"] = port or env.get("MASTER_PORT", "29500")
        if self.ds_config is not None:
            final, _, micro = compute_elastic_config(
                self.ds_config, world_size=world_size, return_microbatch=True)
            env["DS_ELASTIC_TRAIN_BATCH_SIZE"] = str(final)
            env["DS_ELASTIC_MICRO_BATCH_SIZE"] = str(micro)
            env["DS_ELASTIC_WORLD_SIZE"] = str(world_size)
        if self._watchdog is not None:
            from .watchdog import HEARTBEAT_DIR_ENV
            env[HEARTBEAT_DIR_ENV] = self.heartbeat_dir
        return env

    def _backoff_delay(self, restart_count):
        """Exponential restart backoff, capped: restart k waits
        ``restart_backoff · 2^(k-1)`` seconds (0 disables)."""
        if self.restart_backoff <= 0 or restart_count <= 0:
            return 0.0
        return min(self.restart_backoff * (2.0 ** (restart_count - 1)),
                   self.max_restart_backoff)

    def _kill_stalled(self, proc):
        """Terminate a hung worker (escalating to SIGKILL) so the hang
        becomes a restartable failure."""
        logger.error("elastic agent: worker pid %s STALLED (%s); killing",
                     proc.pid, self._watchdog.stall_report())
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()

    def run(self, world_size, rescale=None, coordinator=None):
        """Supervise one local worker; restart on failure up to
        max_restarts as long as the world size stays admissible.

        ``rescale``: optional callback ``(world_size, restart_count) →
        (new_world_size, new_coordinator | None)`` consulted after each
        failure — the TPU-pod rescale story (reference DSElasticAgent's
        torchelastic rendezvous shrink): a dead host's capacity is dropped,
        the batch schedule re-solves for the surviving chip count, and the
        workers restart into a fresh jax.distributed rendezvous, resuming
        from the latest checkpoint.
        """
        while True:
            if not self._validate_world(world_size):
                raise ElasticityIncompatibleWorldSize(
                    f"cannot run with world size {world_size}")
            env = self._elastic_env(world_size, coordinator)
            if self._watchdog is not None:
                self._watchdog.reset()  # stale beats must not vouch for
                                        # the new incarnation
            proc = subprocess.Popen(self.cmd, env=env)
            stalled = False
            while proc.poll() is None:
                time.sleep(self.monitor_interval)
                if self._watchdog is not None and self._watchdog.stalled():
                    self._kill_stalled(proc)
                    stalled = True
                    break
            rc = STALLED if stalled else proc.returncode
            if rc == 0:
                return 0
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                logger.error("elastic agent: max restarts exceeded "
                             "(last failure: %s)", rc)
                # a stall-killed worker may exit 0 from its own SIGTERM
                # handler — a job that died of a stall loop must never
                # report success
                return proc.returncode if proc.returncode else 1
            if rescale is not None:
                new_world, new_coord = rescale(world_size,
                                               self.restart_count)
                if new_world != world_size:
                    logger.warning(
                        "elastic agent: rescaling world %d → %d",
                        world_size, new_world)
                world_size = new_world
                coordinator = new_coord or coordinator
            delay = self._backoff_delay(self.restart_count)
            logger.warning(
                "elastic agent: worker %s rc=%s; restart %d/%d "
                "(world=%d, backoff %.1fs)",
                "stalled" if stalled else "died", rc, self.restart_count,
                self.max_restarts, world_size, delay)
            if delay > 0:
                time.sleep(delay)
