from .elasticity import (compute_elastic_config, elasticity_enabled,
                         ensure_immutable_elastic_config,
                         get_compatible_gpus)
from .constants import (ELASTICITY, ENABLED, ENABLED_DEFAULT,
                        MAX_ACCEPTABLE_BATCH_SIZE,
                        MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT, MICRO_BATCHES,
                        MICRO_BATCHES_DEFAULT)
from .elastic_agent import DSElasticAgent
from .watchdog import HeartbeatMonitor, HeartbeatWriter
