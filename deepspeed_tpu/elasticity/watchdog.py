"""Heartbeat watchdog — hang detection for the elastic restart supervisor.

``DSElasticAgent`` (reference ``elasticity/elastic_agent.py:32``) only
notices a worker that *died*; at pod scale the dominant availability
failure is a worker that *hangs* — a wedged collective, a stuck host, an
NFS stall — which blocks the whole data-parallel group forever while every
process stays alive.  The watchdog closes that gap:

* each worker writes a tiny heartbeat file once per optimizer step (the
  engine does this when ``resilience.watchdog`` is enabled or the agent
  exports ``DS_TPU_HEARTBEAT_DIR``);
* the agent's monitor loop checks heartbeat ages; a worker whose newest
  beat is older than ``stall_timeout`` is killed, which funnels the hang
  into the existing rescale-and-relaunch + checkpoint-resume path.

Writes are atomic (tmp + rename), one file per rank, JSON payload
``{"ts": ..., "step": ..., "pid": ...}`` — cheap enough for every step and
inspectable by humans mid-incident.
"""

import json
import os
import time

from .. import telemetry as _telemetry
from ..utils.fault_injection import fault_point
from ..utils.logging import logger

#: env var the agent exports so workers know where to beat
HEARTBEAT_DIR_ENV = "DS_TPU_HEARTBEAT_DIR"


def _rank_file(directory, rank):
    return os.path.join(directory, f"heartbeat_rank{rank}.json")


class HeartbeatWriter:
    """Worker side: ``beat(step)`` once per optimizer step."""

    def __init__(self, directory, rank=0):
        self.directory = os.path.abspath(directory)
        self.rank = int(rank)
        os.makedirs(self.directory, exist_ok=True)
        self._path = _rank_file(self.directory, self.rank)
        self._last_beat_ts = None

    def beat(self, step):
        if fault_point("heartbeat.beat", rank=self.rank, step=step):
            return False  # injected stall: the worker "hangs"
        now = time.time()
        if self._last_beat_ts is not None and _telemetry.enabled:
            # the worker-side liveness series: how long since the previous
            # beat (≈ optimizer-step cadence; a growing gauge is a stall
            # the agent has not killed yet)
            _telemetry.gauge("elastic/heartbeat_interval_seconds",
                             help="time between this worker's heartbeats"
                             ).set(now - self._last_beat_ts)
            _telemetry.gauge("elastic/heartbeat_step").set(float(step))
        self._last_beat_ts = now
        tmp = self._path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"ts": now, "step": int(step),
                           "pid": os.getpid()}, f)
            os.replace(tmp, self._path)
            return True
        except OSError as e:
            # a failing heartbeat must not kill a healthy training step;
            # the watchdog treats prolonged silence as the signal
            logger.warning("heartbeat write failed (%s); worker will look "
                           "stalled if this persists", e)
            return False


class HeartbeatMonitor:
    """Agent side: judge worker liveness from heartbeat file ages.

    A worker with no heartbeat file yet is measured from ``reset()`` (the
    last (re)launch) — startup compilation counts against the same
    ``stall_timeout``, so set it well above the expected first-step time.

    The directory belongs to ONE agent: ``reset()`` clears every heartbeat
    file in it at each (re)launch, and all ranks found in it are judged
    together.  Point each node's agent at a node-local path (the launcher's
    default per-agent tempdir does this) — a directory shared between
    agents would let one agent's relaunch wipe another's live beats.
    """

    def __init__(self, directory, stall_timeout):
        self.directory = os.path.abspath(directory)
        self.stall_timeout = float(stall_timeout)
        self._epoch = time.time()
        os.makedirs(self.directory, exist_ok=True)

    def reset(self):
        """Call at every (re)launch: clears stale beats from the previous
        incarnation so they don't vouch for the new one."""
        self._epoch = time.time()
        try:
            for name in os.listdir(self.directory):
                if name.startswith("heartbeat_rank"):
                    os.remove(os.path.join(self.directory, name))
        except OSError:
            pass

    def last_beats(self):
        """{rank: payload} for every heartbeat file present."""
        out = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("heartbeat_rank")
                    and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    payload = json.load(f)
                rank = int(name[len("heartbeat_rank"):-len(".json")])
            except (OSError, ValueError):
                continue  # mid-replace race or junk file: skip this scan
            out[rank] = payload
        return out

    def stalled(self, now=None):
        """True when ANY rank's last heartbeat (or, with none yet, the
        launch epoch) is older than ``stall_timeout`` — one hung rank wedges
        the whole collective, so the OLDEST beat is the one that matters
        (a still-beating neighbor must not mask it)."""
        now = time.time() if now is None else now
        beats = self.last_beats()
        if not beats:
            age = now - self._epoch
        else:
            oldest = min(max(p.get("ts", 0.0), self._epoch)
                         for p in beats.values())
            age = now - oldest
        if _telemetry.enabled:
            # agent-side view: age of the OLDEST beat — the number the
            # stall verdict is made from, exported so dashboards can alarm
            # before the kill threshold
            _telemetry.gauge("elastic/heartbeat_age_seconds",
                             help="age of the oldest rank's heartbeat"
                             ).set(age)
        return age > self.stall_timeout

    def stall_report(self, now=None):
        now = time.time() if now is None else now
        beats = self.last_beats()
        if not beats:
            return (f"no heartbeat within {self.stall_timeout:.1f}s of "
                    f"launch (dir={self.directory})")
        lines = [f"rank {r}: step {p.get('step')} "
                 f"{now - p.get('ts', 0.0):.1f}s ago"
                 for r, p in sorted(beats.items())]
        return "; ".join(lines)
