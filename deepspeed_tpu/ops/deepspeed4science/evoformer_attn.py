"""Evoformer (MSA) attention — TPU rebuild of the DS4Sci kernel.

Reference surface: ``deepspeed/ops/deepspeed4science/evoformer_attn.py``
(``DS4Sci_EvoformerAttention``, CUTLASS fMHA fwd/bwd under
``csrc/deepspeed4science/evoformer_attn/``).  The CUDA kernel's point is
memory: attention over MSA tensors ``[B, N, L, H, D]`` with two additive
biases, without materializing the ``[B, N, H, L, L]`` probability tensor.

TPU design: chunked online attention over query blocks.  Each block computes
its scores against the full key axis in fp32, adds the (sliced) biases,
softmaxes, and contracts with V — so peak memory is ``[.., H, block_q, L]``
instead of ``[.., H, L, L]``.  The block function is wrapped in
``jax.checkpoint`` so the backward pass recomputes probabilities instead of
saving them (the flash-backward trade).  All of it is plain jittable JAX —
XLA tiles the two einsums onto the MXU; a hand-written Pallas kernel adds
nothing here because the shapes are static and the fusion is already total.

Bias semantics match the reference exactly (``evoformer_attn.py:88-106``):

* ``biases[0]`` — mask bias, shape ``[B, N, 1, 1, L]`` (broadcast over heads
  and queries; ``-inf``-style key mask).
* ``biases[1]`` — pair bias, shape ``[B, 1, H, L, L]`` (broadcast over the
  MSA row axis).

Gradient contract: the PAIR bias gradient flows on every path.  The MASK
bias gradient flows only on the chunked-XLA path — the Pallas flash route
(taken on TPU when a full pair bias is present, see ``_flash_bias_route``)
treats the mask as a -inf-style constant and returns a ZERO cotangent for
it, like the reference kernel with ``bias1.requires_grad=False``.  Set
``DS_TPU_EVOFORMER_FLASH=0`` to differentiate a trainable mask bias.
"""

import math
import os

import jax
import jax.numpy as jnp


def _split_q_axis(b, n_blocks, block_q):
    """Reshape a bias's query axis (-2) into blocks, or mark it broadcast.

    Returns ``(blocked, static)`` — exactly one is not None.  ``blocked`` has
    the block axis at the front for scanning: ``[nb, ..., block_q, Lk]``.
    """
    if b.shape[-2] == 1:
        return None, b
    *lead, lq, lk = b.shape
    pad = n_blocks * block_q - lq
    if pad:
        b = jnp.pad(b, [(0, 0)] * len(lead) + [(0, pad), (0, 0)])
    b = b.reshape(*lead, n_blocks, block_q, lk)
    return jnp.moveaxis(b, -3, 0), None


def evoformer_attention(q, k, v, biases=(), softmax_scale=None, block_q=256):
    """Gated-MSA-style attention with additive biases.

    Args:
      q, k, v: ``[*, L, H, D]`` (reference layout — heads after sequence).
      biases: tensors broadcastable against scores ``[*, H, Lq, Lk]``.
      softmax_scale: defaults to ``1/sqrt(D)``.
      block_q: query chunk; chosen so the transient score block
        ``[*, H, block_q, L]`` stays small.  ``L <= block_q`` uses the direct
        unchunked path.

    Returns ``[*, L, H, D]`` in ``q.dtype``.
    """
    *_, L, H, D = q.shape
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    dtype = q.dtype
    qh = jnp.moveaxis(q, -2, -3)  # [*, H, L, D]
    kh = jnp.moveaxis(k, -2, -3)
    vh = jnp.moveaxis(v, -2, -3)

    def blk(qb, bias_list):
        # qb: [*, H, bq, D]; full keys. fp32 scores+softmax, dtype matmuls.
        s = jnp.einsum("...qd,...kd->...qk", qb, kh,
                       preferred_element_type=jnp.float32) * scale
        for b in bias_list:
            s = s + b.astype(jnp.float32)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - jax.lax.stop_gradient(m))
        o = jnp.einsum("...qk,...kd->...qd", p.astype(dtype), vh,
                       preferred_element_type=jnp.float32)
        return (o / jnp.sum(p, axis=-1, keepdims=True)).astype(dtype)

    if L <= block_q:
        out = blk(qh, list(biases))
        return jnp.moveaxis(out, -3, -2)

    n_blocks = -(-L // block_q)
    pad = n_blocks * block_q - L
    qp = jnp.pad(qh, [(0, 0)] * (qh.ndim - 2) + [(0, pad), (0, 0)])
    *lead, _, _ = qp.shape
    q_blocks = jnp.moveaxis(
        qp.reshape(*lead, n_blocks, block_q, D), -3, 0)

    scanned, static = [], []
    for b in biases:
        blocked, stat = _split_q_axis(b, n_blocks, block_q)
        if blocked is not None:
            scanned.append(blocked)
        else:
            static.append(stat)

    @jax.checkpoint
    def one(qb, bs):
        return blk(qb, list(bs) + static)

    out = jax.lax.map(lambda args: one(args[0], args[1]),
                      (q_blocks, tuple(scanned)))
    out = jnp.moveaxis(out, 0, -3)             # [*, H, nb, bq, D]
    out = out.reshape(*lead, n_blocks * block_q, D)[..., :L, :]
    return jnp.moveaxis(out, -3, -2)


def _flash_bias_route(Q, K, V, bs):
    """Route full pair-bias attention through the Pallas bias-operand flash
    kernel (``ops/pallas/flash_bias.py``) — the TPU answer to the
    reference's CUTLASS fMHA-with-bias (``csrc/deepspeed4science/
    evoformer_attn/``): dPair comes out of a dedicated in-kernel reduction
    instead of a materialized [B, N, H, L, L] score-grad tensor.

    Returns None when the route doesn't apply (no pair bias, unexpected
    shapes, or non-TPU backend without the env override).  NOTE: on this
    route the MASK bias gets a zero cotangent (it's a -inf-style constant);
    the chunked-XLA path differentiates it if ever needed.
    Env: DS_TPU_EVOFORMER_FLASH=1 forces on (tests, interpret mode), =0 off.
    """
    flag = os.environ.get("DS_TPU_EVOFORMER_FLASH")
    if flag == "0" or os.environ.get("DS_TPU_DISABLE_PALLAS_ATTN"):
        return None  # same fleet-wide kill switch as attention_core
    if flag != "1":
        from ..pallas._common import interpret_mode
        if interpret_mode():
            return None
    B, N, L, H, D = Q.shape
    mask_bias = pair_bias = None
    for b in bs:
        if b.shape[-2] == 1 and b.shape[-3] == 1 and b.shape[1] == N:
            mask_bias = b                      # [B, N, 1, 1, L]
        elif b.shape[1] == 1 and b.shape[-2] == L and b.shape[2] == H:
            pair_bias = b                      # [B, 1, H, L, L]
        else:
            return None
    if pair_bias is None:
        return None
    try:
        from ..pallas.flash_bias import flash_attention_bias
        out = flash_attention_bias(
            Q.reshape(B * N, L, H, D), K.reshape(B * N, L, H, D),
            V.reshape(B * N, L, H, D),
            bias=pair_bias.reshape(B, H, L, L),    # Gb = N batch group
            mask_bias=(None if mask_bias is None
                       else mask_bias.reshape(B * N, 1, 1, L)),
            causal=False)
    except Exception as e:  # kernel construction can fail on real HW —
        from ..attention import _warn_fallback  # same policy as attention_core
        _warn_fallback(e)
        return None
    return out.reshape(B, N, L, H, D)


def DS4Sci_EvoformerAttention(Q, K, V, biases):
    """Reference-parity entry (``evoformer_attn.py:88 DS4Sci_EvoformerAttention``).

    ``Q/K/V``: ``[B, N, L, H, D]`` MSA tensors; ``biases`` a list of at most
    two: mask bias ``[B, N, 1, 1, L]`` then pair bias ``[B, 1, H, L, L]``
    (either may be None/absent).  With a full pair bias on TPU the call
    runs the Pallas bias-operand flash kernel (dBias in-kernel); otherwise
    the chunked-XLA path.
    """
    assert len(biases) <= 2, "at most two biases (mask, pair)"
    bs = [b for b in biases if b is not None]
    B, N, L = Q.shape[0], Q.shape[1], Q.shape[-3]
    for b in bs:
        assert b.shape[-1] == L and b.ndim == Q.ndim, (
            f"bias shape {b.shape} incompatible with Q {Q.shape}")
    out = _flash_bias_route(Q, K, V, bs)
    if out is not None:
        return out
    return evoformer_attention(Q, K, V, biases=bs)
