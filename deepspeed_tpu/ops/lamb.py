"""Fused LAMB — TPU answer to reference ``csrc/lamb/fused_lamb_cuda_kernel.cu``
(``FusedLamb``, ``deepspeed/ops/lamb/fused_lamb.py``).

LAMB = Adam preconditioner + per-layer trust ratio ||p|| / ||update||.
The two norms are tree-wide reductions per parameter — XLA fuses the
reduce + scale into the update loop.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .adam import (GradientTransformation, ScaleByAdamState,
                   no_lr_override, resolve_lr)
from .op_builder import PallasOpBuilder, register_op_builder


def fused_lamb(lr=1e-3,
               betas=(0.9, 0.999),
               eps=1e-8,
               weight_decay=0.0,
               bias_correction=True,
               max_coeff=10.0,
               min_coeff=0.01,
               lr_fn=None):
    """Reference FusedLamb semantics incl. trust-ratio clamping
    (max_coeff/min_coeff match ``deepspeed/ops/lamb/fused_lamb.py`` defaults)."""
    b1, b2 = betas

    def init(params):
        mu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        nu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return ScaleByAdamState(count=jnp.zeros((), jnp.int32), mu=mu, nu=nu,
                                lr_override=no_lr_override())

    def update(grads, state, params):
        count = state.count + 1
        cur_lr = resolve_lr(lr_fn(count) if lr_fn is not None else lr, state)

        def upd(g, m, v, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_ = b1 * m + (1 - b1) * g
            v_ = b2 * v + (1 - b2) * (g * g)
            if bias_correction:
                m_hat = m_ / (1.0 - b1**count.astype(jnp.float32))
                v_hat = v_ / (1.0 - b2**count.astype(jnp.float32))
            else:
                m_hat, v_hat = m_, v_
            u = m_hat / (jnp.sqrt(v_hat) + eps)
            if weight_decay != 0.0:
                u = u + weight_decay * p32
            p_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(u)
            trust = jnp.where(
                (p_norm > 0) & (u_norm > 0),
                jnp.clip(p_norm / u_norm, min_coeff, max_coeff), 1.0)
            return (-cur_lr * trust * u).astype(p.dtype), m_, v_

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_p = treedef.flatten_up_to(params)
        outs = [upd(g, m, v, p) for g, m, v, p in zip(flat_g, flat_m, flat_v, flat_p)]
        return (treedef.unflatten([o[0] for o in outs]),
                ScaleByAdamState(count=count,
                                 mu=treedef.unflatten([o[1] for o in outs]),
                                 nu=treedef.unflatten([o[2] for o in outs]),
                                 lr_override=state.lr_override))

    return GradientTransformation(init=init, update=update)


@register_op_builder
class FusedLambBuilder(PallasOpBuilder):
    NAME = "fused_lamb"
    MODULE = "deepspeed_tpu.ops.lamb"


# Reference import-surface alias (``deepspeed/ops/lamb``).
FusedLamb = fused_lamb
