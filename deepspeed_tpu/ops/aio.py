"""Async I/O op — Python binding for the native ds_aio library.

Reference: ``csrc/aio/py_lib/deepspeed_py_io_handle.cpp`` (``aio_handle``
with pread/pwrite/async variants) + ``op_builder/async_io.py``.
"""

import ctypes

import numpy as np

from .op_builder import NativeOpBuilder, register_op_builder


@register_op_builder
class AsyncIOBuilder(NativeOpBuilder):
    NAME = "async_io"
    SOURCES = ("csrc/aio/ds_aio.cpp", )
    EXTRA_CFLAGS = ("-pthread", )
    EXTRA_LDFLAGS = ("-pthread", )

    def _load_impl(self):
        lib = super()._load_impl()
        lib.ds_aio_handle_new.restype = ctypes.c_void_p
        lib.ds_aio_handle_new.argtypes = [ctypes.c_int64, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_int]
        lib.ds_aio_handle_free.argtypes = [ctypes.c_void_p]
        for fn in (lib.ds_aio_submit_read, lib.ds_aio_submit_write):
            fn.restype = ctypes.c_int64
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
        lib.ds_aio_wait.restype = ctypes.c_int
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ds_aio_pending.restype = ctypes.c_int64
        lib.ds_aio_pending.argtypes = [ctypes.c_void_p]
        for fn in (lib.ds_aio_pread, lib.ds_aio_pwrite):
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_int64, ctypes.c_int64]
        return lib


class AIOHandle:
    """The reference's ``aio_handle`` (queue_depth × block_size parallel
    submission, single/submit/wait API) over the native thread pool."""

    def __init__(self, block_size=1 << 20, queue_depth=32, thread_count=4,
                 single_submit=False, overlap_events=True):
        self._lib = AsyncIOBuilder().load()
        self._h = self._lib.ds_aio_handle_new(block_size, queue_depth,
                                              thread_count, 0)
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.thread_count = thread_count

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ds_aio_handle_free(self._h)
                self._h = None
        except Exception:
            pass

    @staticmethod
    def _buf(arr):
        if not (arr.flags["C_CONTIGUOUS"]):
            raise ValueError("aio buffers must be C-contiguous")
        return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes

    # --- synchronous
    def read(self, arr: np.ndarray, path, offset=0):
        ptr, nbytes = self._buf(arr)
        rc = self._lib.ds_aio_pread(self._h, str(path).encode(), ptr, nbytes,
                                    offset)
        if rc != 0:
            raise IOError(f"aio read failed: {path}")

    def write(self, arr: np.ndarray, path, offset=0):
        ptr, nbytes = self._buf(arr)
        rc = self._lib.ds_aio_pwrite(self._h, str(path).encode(), ptr, nbytes,
                                     offset)
        if rc != 0:
            raise IOError(f"aio write failed: {path}")

    # --- asynchronous
    def async_read(self, arr: np.ndarray, path, offset=0):
        ptr, nbytes = self._buf(arr)
        return self._lib.ds_aio_submit_read(self._h, str(path).encode(), ptr,
                                            nbytes, offset)

    def async_write(self, arr: np.ndarray, path, offset=0):
        ptr, nbytes = self._buf(arr)
        return self._lib.ds_aio_submit_write(self._h, str(path).encode(),
                                             ptr, nbytes, offset)

    def wait(self, request_id):
        rc = self._lib.ds_aio_wait(self._h, request_id)
        if rc != 0:
            raise IOError(f"aio request {request_id} failed (rc={rc})")

    def pending(self):
        return self._lib.ds_aio_pending(self._h)
