"""Async I/O op — Python binding for the native ds_aio library.

Reference: ``csrc/aio/py_lib/deepspeed_py_io_handle.cpp`` (``aio_handle``
with pread/pwrite/async variants) + ``op_builder/async_io.py``.

Two native engines behind one handle API:

* ``uring`` — io_uring queue-depth engine (``csrc/aio/ds_aio_uring.cpp``),
  the analog of the reference's libaio ``deepspeed_aio_thread.cpp``: one
  driver thread keeps ``queue_depth`` block-sized ops in flight in the
  kernel's async submission path.  Default when the kernel allows it.
* ``threads`` — portable thread-pool fallback (``csrc/aio/ds_aio.cpp``).

``engine="auto"`` probes io_uring once per process and falls back cleanly
(containers often disable io_uring via seccomp/sysctl).
"""

import ctypes

import numpy as np

from .op_builder import NativeOpBuilder, register_op_builder

_URING_ALIGN = 4096


@register_op_builder
class AsyncIOBuilder(NativeOpBuilder):
    NAME = "async_io"
    SOURCES = ("csrc/aio/ds_aio.cpp", "csrc/aio/ds_aio_uring.cpp")
    EXTRA_CFLAGS = ("-pthread", )
    EXTRA_LDFLAGS = ("-pthread", )

    def _load_impl(self):
        lib = super()._load_impl()
        lib.ds_aio_handle_new.restype = ctypes.c_void_p
        lib.ds_aio_handle_new.argtypes = [ctypes.c_int64, ctypes.c_int,
                                          ctypes.c_int, ctypes.c_int]
        lib.ds_aio_handle_free.argtypes = [ctypes.c_void_p]
        lib.ds_uring_available.restype = ctypes.c_int
        lib.ds_uring_handle_new.restype = ctypes.c_void_p
        lib.ds_uring_handle_new.argtypes = [ctypes.c_int64, ctypes.c_int,
                                            ctypes.c_int]
        lib.ds_uring_handle_free.argtypes = [ctypes.c_void_p]
        for prefix in ("ds_aio", "ds_uring"):
            for op in ("submit_read", "submit_write"):
                fn = getattr(lib, f"{prefix}_{op}")
                fn.restype = ctypes.c_int64
                fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_void_p, ctypes.c_int64,
                               ctypes.c_int64]
            wait = getattr(lib, f"{prefix}_wait")
            wait.restype = ctypes.c_int
            wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            pend = getattr(lib, f"{prefix}_pending")
            pend.restype = ctypes.c_int64
            pend.argtypes = [ctypes.c_void_p]
            for op in ("pread", "pwrite"):
                fn = getattr(lib, f"{prefix}_{op}")
                fn.restype = ctypes.c_int
                fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_void_p, ctypes.c_int64,
                               ctypes.c_int64]
        return lib


def uring_available():
    """True when the kernel accepts io_uring_setup (it may be compiled in
    but disabled by sysctl/seccomp, common in containers)."""
    try:
        return bool(AsyncIOBuilder().load().ds_uring_available())
    except Exception:
        return False


def aio_aligned_empty(shape, dtype, align=_URING_ALIGN):
    """Like ``np.empty`` but with the buffer start aligned to ``align``
    bytes, qualifying it for O_DIRECT transfers (reference: the pinned
    aligned buffers of ``deepspeed_py_aio_handle``)."""
    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape)) * dtype.itemsize
    raw = np.empty(nbytes + align, dtype=np.uint8)
    start = (-raw.ctypes.data) % align
    return raw[start:start + nbytes].view(dtype).reshape(shape)


class AIOHandle:
    """The reference's ``aio_handle`` (queue_depth × block_size parallel
    submission, single/submit/wait API) over a native engine.

    ``engine``: "auto" (io_uring if the kernel allows, else thread pool),
    "uring", or "threads".  ``o_direct`` applies per-request when buffer,
    offset and length are all 4 KiB-aligned (see ``aio_aligned_empty``)."""

    def __init__(self, block_size=1 << 20, queue_depth=32, thread_count=4,
                 single_submit=False, overlap_events=True, engine="auto",
                 o_direct=False):
        self._lib = AsyncIOBuilder().load()
        self._h = None
        if engine not in ("auto", "uring", "threads"):
            raise ValueError(f"unknown aio engine {engine!r}")
        use_uring = engine in ("auto", "uring") and \
            bool(self._lib.ds_uring_available())
        if engine == "uring" and not use_uring:
            raise RuntimeError("io_uring unavailable on this kernel "
                               "(disabled by sysctl/seccomp?)")
        if use_uring:
            h = self._lib.ds_uring_handle_new(block_size, queue_depth,
                                              1 if o_direct else 0)
            if not h and engine == "uring":
                raise RuntimeError("io_uring ring setup failed")
            use_uring = bool(h)
        if use_uring:
            self.engine = "uring"
            self._h = h
            self._free = self._lib.ds_uring_handle_free
            self._sread = self._lib.ds_uring_submit_read
            self._swrite = self._lib.ds_uring_submit_write
            self._wait = self._lib.ds_uring_wait
            self._pending = self._lib.ds_uring_pending
            self._read = self._lib.ds_uring_pread
            self._write = self._lib.ds_uring_pwrite
        else:
            self.engine = "threads"
            self._h = self._lib.ds_aio_handle_new(block_size, queue_depth,
                                                  thread_count,
                                                  1 if o_direct else 0)
            self._free = self._lib.ds_aio_handle_free
            self._sread = self._lib.ds_aio_submit_read
            self._swrite = self._lib.ds_aio_submit_write
            self._wait = self._lib.ds_aio_wait
            self._pending = self._lib.ds_aio_pending
            self._read = self._lib.ds_aio_pread
            self._write = self._lib.ds_aio_pwrite
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.thread_count = thread_count
        self._live = {}  # request id → buffer (pin across async I/O)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._free(self._h)
                self._h = None
        except Exception:
            pass

    @staticmethod
    def _buf(arr):
        if not (arr.flags["C_CONTIGUOUS"]):
            raise ValueError("aio buffers must be C-contiguous")
        return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes

    # --- synchronous
    def read(self, arr: np.ndarray, path, offset=0):
        ptr, nbytes = self._buf(arr)
        rc = self._read(self._h, str(path).encode(), ptr, nbytes, offset)
        if rc != 0:
            raise IOError(f"aio read failed: {path}")

    def write(self, arr: np.ndarray, path, offset=0):
        ptr, nbytes = self._buf(arr)
        rc = self._write(self._h, str(path).encode(), ptr, nbytes, offset)
        if rc != 0:
            raise IOError(f"aio write failed: {path}")

    # --- asynchronous.  The handle pins the buffer until wait() — dropping
    # the caller's reference mid-flight must not free memory the kernel is
    # still DMA-ing into (the reference pins via its aligned bounce buffers).
    def async_read(self, arr: np.ndarray, path, offset=0):
        ptr, nbytes = self._buf(arr)
        rid = self._sread(self._h, str(path).encode(), ptr, nbytes, offset)
        self._live[rid] = arr
        return rid

    def async_write(self, arr: np.ndarray, path, offset=0):
        ptr, nbytes = self._buf(arr)
        rid = self._swrite(self._h, str(path).encode(), ptr, nbytes, offset)
        self._live[rid] = arr
        return rid

    def wait(self, request_id):
        rc = self._wait(self._h, request_id)
        self._live.pop(request_id, None)
        if rc != 0:
            raise IOError(f"aio request {request_id} failed (rc={rc})")

    def pending(self):
        return self._pending(self._h)
