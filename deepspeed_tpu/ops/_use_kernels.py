"""Shared should-we-run-Pallas gate for kernel dispatch sites."""

import os


def use_pallas_kernels() -> bool:
    """True on real TPU backends (not interpret mode) unless the fleet-wide
    kill switch is set.  DS_TPU_FORCE_PALLAS=1 forces True (tests drive the
    kernels in interpret mode on CPU)."""
    if os.environ.get("DS_TPU_DISABLE_PALLAS_ATTN"):
        return False
    if os.environ.get("DS_TPU_FORCE_PALLAS") == "1":
        return True
    from .pallas._common import interpret_mode
    return not interpret_mode()
