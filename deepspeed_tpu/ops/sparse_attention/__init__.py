"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention/``)."""

from .sparse_self_attention import SparseSelfAttention, sparse_attention
from .sparsity_config import (BigBirdSparsityConfig, BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              SparsityConfig, VariableSparsityConfig)
