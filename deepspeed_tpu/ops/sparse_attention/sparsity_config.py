"""Block-sparsity layout configs (reference
``ops/sparse_attention/sparsity_config.py``: SparsityConfig base + Dense,
Fixed, Variable, BigBird, BSLongformer).

A layout is a boolean block matrix ``[num_heads, nq_blocks, nk_blocks]``
(True = that (q-block, k-block) tile is attended).  The math of each variant
follows the published patterns (Sparse Transformers fixed, BigBird
global+window+random, Longformer sliding+global); the construction below is
written from those definitions, not the reference's tensor code.
"""

import numpy as np


class SparsityConfig:
    """Base: block size + head layout sharing (reference ``:SparsityConfig``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    @property
    def num_layout_heads(self):
        return self.num_heads if self.different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq_len {seq_len} not divisible by block {self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), dtype=bool)

    def _broadcast_heads(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attended (debug/reference parity)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = True
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers 'fixed': local windows of ``num_local_blocks``
    plus column attention to the last ``num_global_blocks`` block(s) of each
    preceding window (reference ``:FixedSparsityConfig``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError("num_local_blocks must be a multiple of "
                             "num_global_blocks")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        causal = self.attention == "unidirectional"
        for h in range(self.num_layout_heads):
            # local windows
            for start in range(0, nb, L):
                end = min(start + L, nb)
                for i in range(start, end):
                    hi = (i + 1) if causal else end
                    layout[h, i, start:hi] = True
            # global columns: representative block(s) of each window
            pat = (h % self.num_different_global_patterns
                   if self.different_layout_per_head else 0)
            for start in range(0, nb, L):
                # last G blocks of the window, shifted by the head pattern
                g_lo = start + L - (pat + 1) * G
                g_hi = g_lo + G
                if g_lo < 0 or g_lo >= nb:
                    continue
                g_hi = min(g_hi, nb)
                if causal:
                    layout[h, g_hi:, g_lo:g_hi] = True
                else:
                    layout[h, :, g_lo:g_hi] = True
                if self.horizontal_global_attention:
                    layout[h, g_lo:g_hi, :] = True
        if causal:
            tri = np.tril(np.ones((nb, nb), dtype=bool))
            layout &= tri
        return self._broadcast_heads(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local window sizes + explicit global block indices + random
    blocks (reference ``:VariableSparsityConfig``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=(4, ),
                 global_block_indices=(0, ), global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)
        self.global_block_end_indices = (
            list(global_block_end_indices) if global_block_end_indices else None)
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        causal = self.attention == "unidirectional"
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_layout_heads):
            # variable local windows: cycle through the given sizes
            start = 0
            wi = 0
            while start < nb:
                w = self.local_window_blocks[
                    min(wi, len(self.local_window_blocks) - 1)]
                end = min(start + w, nb)
                for i in range(start, end):
                    hi = (i + 1) if causal else end
                    layout[h, i, start:hi] = True
                start = end
                wi += 1
            # globals
            if self.global_block_end_indices is None:
                cols = [(i, i + 1) for i in self.global_block_indices]
            else:
                cols = list(zip(self.global_block_indices,
                                self.global_block_end_indices))
            for lo, hi in cols:
                lo, hi = max(lo, 0), min(hi, nb)
                layout[h, :, lo:hi] = True
                if self.horizontal_global_attention:
                    layout[h, lo:hi, :] = True
            # random blocks
            for i in range(nb):
                if self.num_random_blocks:
                    cols_r = rng.choice(nb, size=self.num_random_blocks,
                                        replace=False)
                    layout[h, i, cols_r] = True
        if causal:
            layout &= np.tril(np.ones((nb, nb), dtype=bool))
        return self._broadcast_heads(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + global (reference
    ``:BigBirdSparsityConfig``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional", seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        g = self.num_global_blocks
        causal = self.attention == "unidirectional"
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_layout_heads):
            for i in range(nb):
                layout[h, i, max(0, i - w):min(nb, i + w + 1)] = True
                if self.num_random_blocks:
                    cols = rng.choice(nb, size=self.num_random_blocks,
                                      replace=False)
                    layout[h, i, cols] = True
            layout[h, :, :g] = True     # global columns
            layout[h, :g, :] = True     # global rows
        if causal:
            layout &= np.tril(np.ones((nb, nb), dtype=bool))
        return self._broadcast_heads(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Longformer: sliding window + explicit global blocks (reference
    ``:BSLongformerSparsityConfig``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=(0, ),
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        self.global_block_end_indices = (
            list(global_block_end_indices) if global_block_end_indices else None)
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for i in range(nb):
                layout[h, i, max(0, i - w):min(nb, i + w + 1)] = True
            if self.global_block_end_indices is None:
                cols = [(i, i + 1) for i in self.global_block_indices]
            else:
                cols = list(zip(self.global_block_indices,
                                self.global_block_end_indices))
            for lo, hi in cols:
                lo, hi = max(lo, 0), min(hi, nb)
                layout[h, :, lo:hi] = True
                layout[h, lo:hi, :] = True
        if self.attention == "unidirectional":
            layout &= np.tril(np.ones((nb, nb), dtype=bool))
        return self._broadcast_heads(layout)
