"""Block-sparse self attention (reference
``ops/sparse_attention/sparse_self_attention.py:12`` + the Triton
``matmul.py``/``softmax.py`` kernels).

TPU formulation ("splash-attention-lite"): instead of a hand kernel per
sparse matmul, each q block GATHERS only its allowed k/v blocks (padded to
the layout's max row population) and runs batched MXU matmuls over them —
FLOPs and HBM traffic scale with the number of live blocks, not S².  XLA
fuses the mask/softmax chain; gradients fall out of AD.  A dedicated Pallas
kernel (skip-by-layout inside the flash loop, ``flash_attention.py
_block_live``) is the further optimization once layouts get very sparse.
"""

import numpy as np

import jax
import jax.numpy as jnp

_NEG_INF = float("-inf")


def _row_gather_indices(layout_h):
    """[nq, nk] bool → (idx [nq, maxk], valid [nq, maxk]) with idx padded by
    repeating the first live block (masked out via valid)."""
    nq, nk = layout_h.shape
    counts = layout_h.sum(axis=1)
    maxk = max(1, int(counts.max()))
    idx = np.zeros((nq, maxk), dtype=np.int32)
    valid = np.zeros((nq, maxk), dtype=bool)
    for i in range(nq):
        cols = np.nonzero(layout_h[i])[0]
        idx[i, :len(cols)] = cols
        valid[i, :len(cols)] = True
        if len(cols) == 0:
            valid[i, 0] = False
    return idx, valid


_GATHER_TABLE_CACHE = {}


def layout_gather_tables(layout, num_heads):
    """[H or 1, nq, nk] bool layout → (idx, valid) [H, nq, maxk] host
    arrays, padded to the max row population.  Cached by layout contents —
    the Python row walk runs once per distinct layout, not per forward
    (shared by the gather formulation and the Pallas layout-skip kernel)."""
    layout = np.asarray(layout)
    if layout.shape[0] == 1:
        layout = np.broadcast_to(layout, (num_heads, ) + layout.shape[1:])
    key = (layout.shape, layout.tobytes())
    hit = _GATHER_TABLE_CACHE.get(key)
    if hit is not None:
        return layout, hit[0], hit[1]
    H = layout.shape[0]
    idxs, valids = zip(*(_row_gather_indices(layout[h]) for h in range(H)))
    maxk = max(i.shape[1] for i in idxs)
    idx = np.stack([np.pad(i, ((0, 0), (0, maxk - i.shape[1])))
                    for i in idxs]).astype(np.int32)   # [H, nq, maxk]
    valid = np.stack([np.pad(m, ((0, 0), (0, maxk - m.shape[1])))
                      for m in valids])                # [H, nq, maxk] bool
    if len(_GATHER_TABLE_CACHE) > 64:  # layouts are few; bound anyway
        _GATHER_TABLE_CACHE.clear()
    _GATHER_TABLE_CACHE[key] = (idx, valid)
    return layout, idx, valid


def sparse_attention(q, k, v, layout, block, causal=False, scale=None):
    """q/k/v: [B, S, H, D]; layout: [H or 1, nq, nk] bool (block level).
    Returns [B, S, H, D].
    """
    B, S, H, D = q.shape
    nb = S // block
    scale = scale if scale is not None else D ** -0.5
    layout, idx, valid = layout_gather_tables(layout, H)
    maxk = idx.shape[2]

    qb = q.reshape(B, nb, block, H, D).transpose(3, 0, 1, 2, 4)  # [H,B,nq,bs,D]
    kb = k.reshape(B, nb, block, H, D).transpose(3, 0, 1, 2, 4)
    vb = v.reshape(B, nb, block, H, D).transpose(3, 0, 1, 2, 4)
    idx_j = jnp.asarray(idx)
    valid_j = jnp.asarray(valid)

    def per_head(qh, kh, vh, idx_h, valid_h):
        # gather each q block's allowed k/v blocks: [B, nq, maxk, bs, D]
        kg = kh[:, idx_h]
        vg = vh[:, idx_h]
        s = jnp.einsum("bqtd,bqkcd->bqtkc", qh.astype(jnp.float32),
                       kg.astype(jnp.float32)) * scale
        # mask: padding blocks; causal within/between blocks
        mask = valid_h[None, :, None, :, None]
        if causal:
            qpos = (jnp.arange(nb)[:, None] * block
                    + jnp.arange(block)[None, :])        # [nq, bs]
            kpos = idx_h[:, :, None] * block + jnp.arange(block)  # [nq,maxk,bs]
            cm = qpos[:, :, None, None] >= kpos[:, None, :, :]
            mask = jnp.logical_and(mask, cm[None])
        mask = jnp.broadcast_to(mask, s.shape)
        s = jnp.where(mask, s, _NEG_INF)
        flat = s.shape[:3] + (maxk * block, )
        sf = s.reshape(flat)
        m = jnp.max(sf, axis=-1, keepdims=True)
        m = jnp.where(m == _NEG_INF, 0.0, m)
        p = jnp.where(mask.reshape(flat), jnp.exp(sf - m), 0.0)
        denom = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        p = (p / denom).reshape(s.shape)
        return jnp.einsum("bqtkc,bqkcd->bqtd", p, vg.astype(jnp.float32))

    out = jax.vmap(per_head)(qb, kb, vb, idx_j, valid_j)  # [H,B,nq,bs,D]
    return out.transpose(1, 2, 3, 0, 4).reshape(B, S, H, D).astype(q.dtype)


class SparseSelfAttention:
    """Reference ``SparseSelfAttention`` API: configure once with a
    SparsityConfig, call with [B, H, S, D] tensors (reference layout) or
    [B, S, H, D] (``bshd=True``)."""

    def __init__(self, sparsity_config, key_padding_mask_mode="add",
                 attn_mask_mode="mul", max_seq_length=2048):
        self.sparsity_config = sparsity_config
        self.max_seq_length = max_seq_length
        self._layouts = {}

    def layout(self, seq_len):
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, bshd=False, causal=None):
        q, k, v = (jnp.asarray(t) for t in (query, key, value))
        if not bshd:  # reference [B, H, S, D] → internal [B, S, H, D]
            q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        S = q.shape[1]
        if causal is None:
            causal = self.sparsity_config.attention == "unidirectional" \
                if hasattr(self.sparsity_config, "attention") else False
        block = self.sparsity_config.block
        fn = sparse_attention
        from .._use_kernels import use_pallas_kernels
        if use_pallas_kernels() and S % block == 0:
            # TPU: stream only the live blocks (Pallas layout-skip kernel)
            # instead of materializing the gathered K/V copy
            from ..pallas.block_sparse_attention import (
                block_sparse_flash_attention)
            fn = block_sparse_flash_attention
        out = fn(q, k, v, self.layout(S), block, causal=causal)
        return out if bshd else out.transpose(0, 2, 1, 3)
