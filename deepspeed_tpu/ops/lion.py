"""Fused Lion — TPU answer to reference ``csrc/lion/multi_tensor_lion.cu`` +
``cpu_lion.cpp`` (``FusedLion``/``DeepSpeedCPULion``).

Lion: sign-of-interpolated-momentum update; decoupled weight decay.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .adam import GradientTransformation, no_lr_override, resolve_lr
from .op_builder import PallasOpBuilder, register_op_builder


class ScaleByLionState(NamedTuple):
    count: jnp.ndarray
    mu: any
    lr_override: any = None  # see ScaleByAdamState.lr_override


def fused_lion(lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0, lr_fn=None):
    b1, b2 = betas

    def init(params):
        mu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return ScaleByLionState(count=jnp.zeros((), jnp.int32), mu=mu,
                                lr_override=no_lr_override())

    def update(grads, state, params):
        count = state.count + 1
        cur_lr = resolve_lr(lr_fn(count) if lr_fn is not None else lr, state)

        def upd(g, m, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            c = b1 * m + (1 - b1) * g
            step = jnp.sign(c)
            if weight_decay != 0.0:
                step = step + weight_decay * p32
            m_ = b2 * m + (1 - b2) * g
            return (-cur_lr * step).astype(p.dtype), m_

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_p = treedef.flatten_up_to(params)
        outs = [upd(g, m, p) for g, m, p in zip(flat_g, flat_m, flat_p)]
        return (treedef.unflatten([o[0] for o in outs]),
                ScaleByLionState(count=count,
                                 mu=treedef.unflatten([o[1] for o in outs]),
                                 lr_override=state.lr_override))

    return GradientTransformation(init=init, update=update)


def sgd(lr=1e-3, momentum=0.0, weight_decay=0.0, nesterov=False, lr_fn=None):
    """Plain SGD (reference maps config "sgd" to torch.optim.SGD)."""

    def init(params):
        if momentum == 0.0:
            return ScaleByLionState(count=jnp.zeros((), jnp.int32), mu=(),
                                    lr_override=no_lr_override())
        mu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return ScaleByLionState(count=jnp.zeros((), jnp.int32), mu=mu,
                                lr_override=no_lr_override())

    def update(grads, state, params):
        count = state.count + 1
        cur_lr = resolve_lr(lr_fn(count) if lr_fn is not None else lr, state)

        def upd(g, m, p):
            g = g.astype(jnp.float32)
            if weight_decay != 0.0:
                g = g + weight_decay * p.astype(jnp.float32)
            if momentum == 0.0:
                return (-cur_lr * g).astype(p.dtype), m
            m_ = momentum * m + g
            d = (g + momentum * m_) if nesterov else m_
            return (-cur_lr * d).astype(p.dtype), m_

        if momentum == 0.0:
            updates = jax.tree_util.tree_map(
                lambda g, p: upd(g, None, p)[0], grads, params)
            return updates, ScaleByLionState(count=count, mu=state.mu,
                                             lr_override=state.lr_override)
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_p = treedef.flatten_up_to(params)
        outs = [upd(g, m, p) for g, m, p in zip(flat_g, flat_m, flat_p)]
        return (treedef.unflatten([o[0] for o in outs]),
                ScaleByLionState(count=count,
                                 mu=treedef.unflatten([o[1] for o in outs]),
                                 lr_override=state.lr_override))

    return GradientTransformation(init=init, update=update)


@register_op_builder
class FusedLionBuilder(PallasOpBuilder):
    NAME = "fused_lion"
    MODULE = "deepspeed_tpu.ops.lion"


@register_op_builder
class CPULionBuilder(PallasOpBuilder):
    NAME = "cpu_lion"
    MODULE = "deepspeed_tpu.ops.lion"


# Reference import-surface aliases (``deepspeed/ops/lion``).
FusedLion = fused_lion
DeepSpeedCPULion = fused_lion
