"""Attention core — dispatch layer for the attention kernels.

Role of the reference's fused attention kernels (``csrc/transformer/inference``
softmax/attention ops and the FastGen blocked flash, SURVEY.md §2.2): a single
entry point the models call; on TPU it routes to the Pallas flash-attention
kernel, elsewhere (CPU tests) to a plain XLA implementation that compiles to
the same math.
"""

import os
from functools import partial

import jax
import jax.numpy as jnp


def _xla_attention(q, k, v, causal=True, softmax_scale=None, window=0,
                   alibi_slopes=None):
    """Reference XLA path [B, S, H, D] (fp32 softmax accumulation)."""
    B, S, H, D = q.shape
    scale = softmax_scale if softmax_scale is not None else D**-0.5
    logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
    if alibi_slopes is not None:
        # ALiBi (softmax-invariant form: + slope_h * key_pos) in fp32 —
        # bf16 quantizes slope*position to useless resolution past ~256
        # (and the decode path computes it in fp32; they must agree).
        # Slopes are positional constants, never trained (matches the
        # flash kernel's stop_gradient).
        logits = logits.astype(jnp.float32)
        sl = jax.lax.stop_gradient(jnp.asarray(alibi_slopes, jnp.float32))
        logits = logits + sl[None, :, None, None] \
            * jnp.arange(k.shape[1], dtype=jnp.float32)[None, None, None, :]
    if causal:
        Sk = k.shape[1]
        mask = jnp.tril(jnp.ones((S, Sk), dtype=bool), k=Sk - S)
        if window:
            # sliding window: each query sees only the last `window` keys
            mask &= ~jnp.tril(jnp.ones((S, Sk), dtype=bool),
                              k=Sk - S - window)
        logits = jnp.where(mask[None, None], logits,
                           jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def _use_pallas():
    # one shared gate for every Pallas dispatch site (kill switch,
    # interpret-mode detection, DS_TPU_FORCE_PALLAS for CPU tests)
    from ._use_kernels import use_pallas_kernels
    return use_pallas_kernels()


_fallback_warned = False


def _warn_fallback(e):
    """LOUD once: silently trading the flash kernel for O(S²)-memory XLA
    attention would destroy MFU on real hardware."""
    global _fallback_warned
    if not _fallback_warned:
        _fallback_warned = True
        from ..utils.logging import logger
        logger.warning(
            "Pallas flash attention unavailable/failed on this platform "
            "(%s: %s) — falling back to XLA attention; expect lower MFU "
            "at long sequence lengths", type(e).__name__, e)


def attention_core(q, k, v, causal=True, softmax_scale=None, window=0,
                   alibi_slopes=None):
    """[B, S, H, D] attention; flash kernel on TPU, XLA elsewhere.
    ``window`` > 0 = sliding-window causal attention (Mistral)."""
    if window and not causal:
        # validate BEFORE dispatch: the flash path rejects this combination
        # and the XLA path used to silently ignore the window — both
        # backends must fail identically (round-2 advisor finding)
        raise ValueError("window > 0 requires causal=True (sliding-window "
                         "attention is defined over causal positions)")
    if _use_pallas():
        try:
            from .pallas.flash_attention import (DEFAULT_BLOCK_K,
                                                 DEFAULT_BLOCK_Q,
                                                 flash_attention)
        except Exception as e:  # import failure → documented XLA fallback
            flash_attention = None
            _warn_fallback(e)
        if flash_attention is not None:
            # parse OUTSIDE the kernel-fallback guard — a malformed env
            # value should fail fast, not silently disable the kernel
            bq = int(os.environ.get("DS_TPU_FLASH_BLOCK_Q",
                                    DEFAULT_BLOCK_Q))
            bk = int(os.environ.get("DS_TPU_FLASH_BLOCK_K",
                                    DEFAULT_BLOCK_K))
            try:
                return flash_attention(q, k, v, causal=causal,
                                       softmax_scale=softmax_scale,
                                       window=window, block_q=bq,
                                       block_k=bk,
                                       alibi_slopes=alibi_slopes)
            except Exception as e:
                _warn_fallback(e)
    return _xla_attention(q, k, v, causal=causal, softmax_scale=softmax_scale,
                          window=window, alibi_slopes=alibi_slopes)
