"""Fused Adam/AdamW — TPU answer to reference ``csrc/adam/multi_tensor_adam.cu``
+ ``deepspeed/ops/adam/fused_adam.py:18`` (FusedAdam) and
``cpu_adam.cpp`` (DeepSpeedCPUAdam, reference ``csrc/adam``).

Design: optax-style ``GradientTransformation`` whose update math is a single
fused elementwise region — XLA fuses the whole tree update into one kernel per
buffer, which on TPU matches what multi-tensor-apply achieves on CUDA.  A
Pallas variant (``deepspeed_tpu.ops.pallas.fused_adam``) exists for the cases
XLA's fusion falls short (interleaved master-weight cast + update).

The ``step`` counter lives in the optimizer state (bias correction), matching
``FusedAdam``'s semantics (bias_correction=True, adam_w_mode=True by default).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .op_builder import PallasOpBuilder, register_op_builder


class ScaleByAdamState(NamedTuple):
    count: jnp.ndarray  # int32 scalar
    mu: any
    nu: any
    # f32 scalar; NaN = follow the configured lr/schedule.  A runtime state
    # leaf (not a baked constant) so torch-API writes to
    # ``optimizer.param_groups[0]["lr"]`` take effect in the already-compiled
    # step without recompilation (reference FusedAdam honors such writes).
    lr_override: any = None


class GradientTransformation(NamedTuple):
    """Minimal optax-compatible pair (init, update)."""
    init: callable
    update: callable


def _bias_correction(decay, count):
    return 1.0 - decay**count


def no_lr_override():
    """Initial ``lr_override`` leaf: NaN = follow the configured schedule."""
    return jnp.full((), jnp.nan, jnp.float32)


def resolve_lr(cur_lr, state):
    """Effective lr: the runtime ``lr_override`` state leaf when set (via
    ``optimizer.param_groups[0]['lr'] = x``), else the schedule's value."""
    ov = getattr(state, "lr_override", None)
    if ov is None:
        return cur_lr
    return jnp.where(jnp.isnan(ov), cur_lr, ov)


def fused_adam(lr=1e-3,
               betas=(0.9, 0.999),
               eps=1e-8,
               weight_decay=0.0,
               adam_w_mode=True,
               bias_correction=True,
               lr_fn=None):
    """FusedAdam/FusedAdamW (reference ``ops/adam/fused_adam.py:18``).

    ``lr_fn``: optional schedule step→lr overriding ``lr`` (engine wires the
    LR scheduler through this).
    """
    b1, b2 = betas

    def init(params):
        mu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        nu = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return ScaleByAdamState(count=jnp.zeros((), jnp.int32), mu=mu, nu=nu,
                                lr_override=no_lr_override())

    def update(grads, state, params):
        count = state.count + 1
        cur_lr = resolve_lr(lr_fn(count) if lr_fn is not None else lr, state)

        def upd(g, m, v, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not adam_w_mode and weight_decay != 0.0:
                g = g + weight_decay * p32  # L2 mode (reference adam_w_mode=False)
            m_ = b1 * m + (1 - b1) * g
            v_ = b2 * v + (1 - b2) * (g * g)
            if bias_correction:
                m_hat = m_ / _bias_correction(b1, count.astype(jnp.float32))
                v_hat = v_ / _bias_correction(b2, count.astype(jnp.float32))
            else:
                m_hat, v_hat = m_, v_
            step = m_hat / (jnp.sqrt(v_hat) + eps)
            if adam_w_mode and weight_decay != 0.0:
                step = step + weight_decay * p32  # decoupled decay
            return (-cur_lr * step).astype(p.dtype), m_, v_

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_p = treedef.flatten_up_to(params)
        outs = [upd(g, m, v, p) for g, m, v, p in zip(flat_g, flat_m, flat_v, flat_p)]
        updates = treedef.unflatten([o[0] for o in outs])
        mu = treedef.unflatten([o[1] for o in outs])
        nu = treedef.unflatten([o[2] for o in outs])
        return updates, ScaleByAdamState(count=count, mu=mu, nu=nu,
                                         lr_override=state.lr_override)

    return GradientTransformation(init=init, update=update)


def fused_adamw(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01,
                **kw):
    return fused_adam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
                      adam_w_mode=True, **kw)


def cpu_adam(*args, **kwargs):
    """DeepSpeedCPUAdam analog: same math; the *placement* (host memory) is
    decided by the ZeRO-Offload sharding policy, not the optimizer (reference
    keeps a separate AVX C++ impl because torch CPU Adam is slow; XLA:CPU
    vectorizes this fine)."""
    return fused_adam(*args, **kwargs)


# Reference import-surface aliases (``deepspeed/ops/adam/fused_adam.py:18``,
# ``cpu_adam.py``): migrating code does ``from deepspeed.ops.adam import
# FusedAdam`` — here these are the gradient-transformation constructors,
# which ``initialize(optimizer=...)`` accepts directly.
FusedAdam = fused_adam
FusedAdamW = fused_adamw
DeepSpeedCPUAdam = cpu_adam


@register_op_builder
class FusedAdamBuilder(PallasOpBuilder):
    NAME = "fused_adam"
    MODULE = "deepspeed_tpu.ops.adam"


@register_op_builder
class CPUAdamBuilder(PallasOpBuilder):
    NAME = "cpu_adam"
    MODULE = "deepspeed_tpu.ops.adam"
