"""Shared Pallas helpers."""

import functools
import os

import jax


@functools.cache
def interpret_mode() -> bool:
    """True → run Pallas kernels in interpreter mode (non-TPU backends).

    Checks the default device's platform AND device_kind: proxied PJRT
    plugins (e.g. the remote-TPU 'axon' platform) may expose a platform
    string that isn't literally "tpu" while still being a real TPU — running
    Mosaic kernels interpreted there would silently destroy performance.

    ``DS_TPU_PALLAS_INTERPRET=0|1`` overrides the probe entirely — needed
    by AOT compile-checks (tools/aot_kernel_check.py), which target a TPU
    topology while the DEFAULT backend is CPU (and the probe's
    jax.devices() can block on a dark device tunnel).
    """
    forced = os.environ.get("DS_TPU_PALLAS_INTERPRET")
    if forced is not None:
        return forced not in ("0", "false", "False")
    try:
        dev = jax.devices()[0]
    except Exception:
        return True
    kind = (getattr(dev, "device_kind", "") or "").lower()
    return not ("tpu" in dev.platform.lower() or "tpu" in kind)
