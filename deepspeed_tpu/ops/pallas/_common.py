"""Shared Pallas helpers."""

import functools

import jax


@functools.cache
def interpret_mode() -> bool:
    """True → run Pallas kernels in interpreter mode (non-TPU backends).

    Checks the default device's platform AND device_kind: proxied PJRT
    plugins (e.g. the remote-TPU 'axon' platform) may expose a platform
    string that isn't literally "tpu" while still being a real TPU — running
    Mosaic kernels interpreted there would silently destroy performance.
    """
    try:
        dev = jax.devices()[0]
    except Exception:
        return True
    kind = (getattr(dev, "device_kind", "") or "").lower()
    return not ("tpu" in dev.platform.lower() or "tpu" in kind)
