"""Grouped (MoE expert) matmul — Pallas TPU kernel.

TPU answer to the reference's FastGen MoE kernel suite
(``inference/v2/kernels/cutlass_ops/grouped_gemm`` + ``moe_scatter``/
``moe_gather``): tokens sorted by expert multiply that expert's weight
matrix, one MXU-tiled pass over all experts.

Design (megablocks-style, guided by the group-padding trick):

* each group is padded up to a multiple of ``block_m`` INSIDE the call
  (vectorized scatter by destination index), so every row-tile belongs to
  exactly ONE expert — no straddling, no masked accumulation;
* the per-tile expert id is a scalar-prefetch operand: the kernel's
  ``w`` BlockSpec index_map reads ``expert_of_tile[m]`` to page the right
  expert's [block_k, block_n] weight tile into VMEM while the MXU chews the
  previous tile (the same scalar-prefetch pattern as the paged-attention
  kernel);
* grid (m, n, k) with k innermost accumulating into an f32 VMEM scratch.

XLA's native ``lax.ragged_dot`` serves the same role (and is the default —
``moe_expert_ffn`` keeps it unless ``DS_TPU_MOE_GMM=1``); this kernel exists
so the MoE path has a hand-schedulable alternative to A/B on real hardware
(``tools/kernel_bench`` pattern), exactly how the reference ships a CUTLASS
grouped GEMM next to cuBLAS.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_mode as _interpret


def _gmm_kernel(expert_ref, x_ref, w_ref, y_ref, acc_ref, *, nk):
    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(x_ref[...], w_ref[0],
                            preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == nk - 1)
    def _flush():
        y_ref[...] = acc_ref[...].astype(y_ref.dtype)


def _pad_layout(group_sizes, T, E, block_m):
    """Vectorized group-padding layout.

    Returns (dest_idx [T], expert_of_tile [Tp_max//block_m], Tp_max) where
    row i of the sorted input lands at padded row dest_idx[i], and tile t of
    the padded buffer belongs to expert expert_of_tile[t].  Tp_max is the
    STATIC bound T_pad = ceil(T/bm)*bm + E*bm (shapes stay static under
    jit; tiles past the live data compute into padding rows that the final
    gather drops)."""
    sizes = group_sizes.astype(jnp.int32)
    starts = jnp.concatenate([jnp.zeros((1, ), jnp.int32),
                              jnp.cumsum(sizes)[:-1]])
    padded = ((sizes + block_m - 1) // block_m) * block_m
    pstarts = jnp.concatenate([jnp.zeros((1, ), jnp.int32),
                               jnp.cumsum(padded)[:-1]])
    rows = jnp.arange(T, dtype=jnp.int32)
    g_of_row = jnp.searchsorted(jnp.cumsum(sizes), rows, side="right"
                                ).astype(jnp.int32)
    dest = pstarts[g_of_row] + (rows - starts[g_of_row])
    tp_max = ((T + block_m - 1) // block_m) * block_m + E * block_m
    tiles = jnp.arange(tp_max // block_m, dtype=jnp.int32)
    pends_tiles = jnp.cumsum(padded) // block_m        # [E]
    expert_of_tile = jnp.minimum(
        jnp.searchsorted(pends_tiles, tiles, side="right"),
        E - 1).astype(jnp.int32)
    return dest, expert_of_tile, tp_max


@functools.partial(jax.jit, static_argnames=("block_m", "block_n", "block_k",
                                             "interpret"))
def gmm(x, w, group_sizes, *, block_m=128, block_n=128, block_k=128,
        interpret=None):
    """Grouped matmul: ``y[i] = x[i] @ w[g(i)]``.

    x: [T, K] with rows SORTED by group (group g's rows contiguous);
    w: [E, K, N]; group_sizes: [E] summing to T.  Returns [T, N].
    """
    T, K = x.shape
    E, Kw, N = w.shape
    assert K == Kw, (K, Kw)
    if interpret is None:
        interpret = _interpret()
    if K % block_k or N % block_n:
        raise ValueError(f"K={K} / N={N} must divide block_k/{block_k} "
                         f"block_n/{block_n}")
    dest, expert_of_tile, tp = _pad_layout(group_sizes, T, E, block_m)
    xp = jnp.zeros((tp, K), x.dtype).at[dest].set(x)

    nk = K // block_k
    grid = (tp // block_m, N // block_n, nk)
    yp = pl.pallas_call(
        functools.partial(_gmm_kernel, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_m, block_k),
                             lambda m, n, k, e: (m, k)),
                pl.BlockSpec((1, block_k, block_n),
                             lambda m, n, k, e: (e[m], k, n)),
            ],
            out_specs=pl.BlockSpec((block_m, block_n),
                                   lambda m, n, k, e: (m, n)),
            scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((tp, N), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(expert_of_tile, xp, w)
    return yp[dest]
