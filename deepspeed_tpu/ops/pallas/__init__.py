"""Pallas TPU kernels — the native-kernel layer (L1).

TPU answer to the reference's ``csrc/`` CUDA tree (SURVEY.md §2.2): where the
reference JIT-compiles .cu files through the op-builder, we ship Pallas
(Mosaic) kernels compiled by XLA.  Each kernel has an interpret-mode path so
the numerics tests run on CPU (the analog of the reference's per-kernel
numerics tests vs a torch oracle, ``tests/unit/ops/``).

Kernels:
  flash_attention — blockwise online-softmax attention (fwd+bwd), the analog
      of csrc/transformer/inference softmax+attention and the FastGen
      blocked-flash kernels.
  optimizers — fused Adam/Lion/LAMB elementwise update kernels with
      interleaved master-weight cast (csrc/adam/multi_tensor_adam.cu,
      csrc/lion, csrc/lamb).
  quantizer — blockwise int8/int4 (de)quantization (csrc/quantization) used
      by ZeRO++ qwZ/qgZ and weight-only inference quant.
"""

from .flash_attention import flash_attention
from .quantizer import quantize_blockwise, dequantize_blockwise
from .optimizers import (fused_adam_step, fused_lion_step, fused_lamb_step)
