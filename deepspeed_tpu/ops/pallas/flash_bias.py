"""Flash attention with a trainable additive bias operand (dBias output).

Closes the reference's last kernel family: ``csrc/deepspeed4science/
evoformer_attn/`` (14.9k LoC CUTLASS fMHA) exists precisely because
attention-with-bias *and grad-of-bias* doesn't flash-fuse for free — the
bias gradient is the full score-gradient tensor, which a naive AD
materializes at [B, H, Sq, Sk].

TPU design (three-kernel flash, same recurrence as ``flash_attention.py``):

* forward: online softmax over K blocks with ``s = scale·qkᵀ + bias
  (+ mask_bias)``; bias tiles stream through VMEM like K/V — the score
  tensor never exists in HBM;
* backward dq / dkv: standard flash recomputation with the bias re-added;
* backward **dbias**: a dedicated reduction kernel.  The bias may be
  *broadcast-grouped* over batch and heads (shape ``[Bb, Hb, Sq, Sk]``
  against ``B = Bb·Gb`` kernel batches and ``H = Hb·Gh`` heads — the
  evoformer pair bias is ``[B, 1, H, L, L]`` over an ``N``-row MSA batch,
  i.e. Gb = N).  The group dims are the innermost *arbitrary* grid axes, so
  each bias tile accumulates ``Σ_g ds`` in VMEM scratch across consecutive
  grid steps and is written once — dBias comes out at the bias's own
  (reduced) shape and the [B, H, Sq, Sk] tensor is never materialized.

``mask_bias`` ([B, 1, 1, Sk], e.g. the evoformer MSA key mask) is additive
but NON-differentiable (stop-gradient semantics, like ALiBi slopes): its
cotangent is defined as zero on this path.  Mask biases are -inf-style
validity masks; train a mask through the chunked-XLA path if ever needed.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_mode as _interpret
from .flash_attention import (_DEAD_ROW_LSE, _NEG_INF, _col_to_row, _pad_to,
                              _row_to_col, _score_mask)

# bias tiles add a (block_q, block_k) f32 VMEM resident per kernel — default
# to 256 tiles (0.25 MB each) rather than the biasless kernel's 512.
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


def _load_bias(bias_ref, mask_ref, s, have_mask):
    """s + bias tile (+ mask row, broadcast over the q sublanes)."""
    s = s + bias_ref[0, 0].astype(jnp.float32)
    if have_mask:
        s = s + mask_ref[0, 0].astype(jnp.float32)  # [1, block_k] row
    return s


# --------------------------------------------------------------------- fwd
def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, mask_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, sq, sk, block_q,
                block_k, have_mask):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start, k_start = iq * block_q, ik * block_k
    live = (jnp.logical_and(k_start < sk,
                            k_start <= q_start + block_q - 1 + (sk - sq))
            if causal else k_start < sk)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _load_bias(bias_ref, mask_ref, s, have_mask)
        mask = _score_mask(q_start, k_start, causal, sq, sk, block_q, block_k)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        p = jnp.where(mask, jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - m_safe))
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        m = m_ref[:, :1]
        lse = jnp.where(m == _NEG_INF, _DEAD_ROW_LSE, m + jnp.log(l_safe))
        lse_ref[0, 0] = _col_to_row(lse)  # packed [.., 1, S]


def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    bias_ref, mask_ref, *, scale, causal, sq, sk, block_q,
                    block_k, q_start, k_start, have_mask):
    """Shared bwd recomputation: returns (p, ds_score) for one tile.
    ``ds_score`` is d(loss)/d(score) — multiply by ``scale`` for dq/dk,
    use as-is for dbias."""
    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = _row_to_col(lse_ref[0, 0])
    delta = _row_to_col(delta_ref[0, 0])
    s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = _load_bias(bias_ref, mask_ref, s, have_mask)
    mask = _score_mask(q_start, k_start, causal, sq, sk, block_q, block_k)
    p = jnp.where(mask, jnp.exp(s - lse), 0.0)
    dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                             preferred_element_type=jnp.float32)
    return p, do, p * (dp - delta)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
                   mask_ref, dq_ref, acc_ref, *, scale, causal, sq, sk,
                   block_q, block_k, have_mask):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start, k_start = iq * block_q, ik * block_k
    live = (jnp.logical_and(k_start < sk,
                            k_start <= q_start + block_q - 1 + (sk - sq))
            if causal else k_start < sk)

    @pl.when(live)
    def _compute():
        _, _, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
            mask_ref, scale=scale, causal=causal, sq=sq, sk=sk,
            block_q=block_q, block_k=block_k, q_start=q_start,
            k_start=k_start, have_mask=have_mask)
        k = k_ref[0, 0].astype(jnp.float32)
        acc_ref[:] += jax.lax.dot(ds * scale, k,
                                  preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    bias_ref, mask_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale, causal, sq, sk, block_q, block_k, have_mask):
    ik, iq = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start, k_start = iq * block_q, ik * block_k
    live = (jnp.logical_and(k_start < sk,
                            k_start <= q_start + block_q - 1 + (sk - sq))
            if causal else k_start < sk)

    @pl.when(live)
    def _compute():
        p, do, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
            mask_ref, scale=scale, causal=causal, sq=sq, sk=sk,
            block_q=block_q, block_k=block_k, q_start=q_start,
            k_start=k_start, have_mask=have_mask)
        q = q_ref[0, 0].astype(jnp.float32)
        dv_acc[:] += jax.lax.dot_general(p, do, (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(ds * scale, q,
                                         (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dbias_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      bias_ref, mask_ref, dbias_ref, acc_ref, *, scale,
                      causal, sq, sk, block_q, block_k, gb, gh, have_mask):
    """dBias at the bias's own (broadcast-grouped) resolution: the two
    innermost grid dims walk the (batch, head) group members and accumulate
    ``ds_score`` into VMEM scratch; one write per bias tile."""
    iq, ik = pl.program_id(2), pl.program_id(3)
    igb, igh = pl.program_id(4), pl.program_id(5)

    @pl.when(jnp.logical_and(igb == 0, igh == 0))
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start, k_start = iq * block_q, ik * block_k
    live = (jnp.logical_and(k_start < sk,
                            k_start <= q_start + block_q - 1 + (sk - sq))
            if causal else k_start < sk)

    @pl.when(live)
    def _compute():
        _, _, ds = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref,
            mask_ref, scale=scale, causal=causal, sq=sq, sk=sk,
            block_q=block_q, block_k=block_k, q_start=q_start,
            k_start=k_start, have_mask=have_mask)
        acc_ref[:] += ds

    @pl.when(jnp.logical_and(igb == gb - 1, igh == gh - 1))
    def _finish():
        dbias_ref[0, 0] = acc_ref[:].astype(dbias_ref.dtype)


# ----------------------------------------------------------------- drivers
def _specs(B, Hq, bias_shape, mask_shape, block_q, block_k, D, order="qk"):
    """BlockSpecs shared by fwd/dq (grid b,h,iq,ik) or dkv (grid b,h,ik,iq).
    The bias index map folds broadcast groups: bias batch bb = b // Gb,
    bias head hb = h // Gh."""
    Bb, Hb = bias_shape[0], bias_shape[1]
    Gb, Gh = B // Bb, Hq // Hb
    if order == "qk":
        qi, ki = (lambda i, j: i), (lambda i, j: j)
    else:
        qi, ki = (lambda i, j: j), (lambda i, j: i)
    qspec = pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i, j: (b, h, qi(i, j), 0))
    kspec = pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, h, ki(i, j), 0))
    bias_spec = pl.BlockSpec(
        (1, 1, block_q, block_k),
        lambda b, h, i, j: (b // Gb, h // Gh, qi(i, j), ki(i, j)))
    Gm = B // mask_shape[0]
    mask_spec = pl.BlockSpec(
        (1, 1, 1, block_k),
        lambda b, h, i, j: (b // Gm, 0, 0, ki(i, j)))
    row_spec = pl.BlockSpec((1, 1, 1, block_q),
                            lambda b, h, i, j: (b, h, 0, qi(i, j)))
    return qspec, kspec, bias_spec, mask_spec, row_spec


def _fwd(q, k, v, bias, mask_bias, causal, scale, block_q, block_k, sq, sk):
    B, Hq, sq_p, D = q.shape
    nq, nk = sq_p // block_q, k.shape[2] // block_k
    have_mask = mask_bias is not None
    mask_op = (mask_bias if have_mask
               else jnp.zeros((1, 1, 1, k.shape[2]), jnp.float32))
    qspec, kspec, bias_spec, mask_spec, row_spec = _specs(
        B, Hq, bias.shape, mask_op.shape, block_q, block_k, D)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, sq=sq,
                          sk=sk, block_q=block_q, block_k=block_k,
                          have_mask=have_mask),
        grid=(B, Hq, nq, nk),
        in_specs=[qspec, kspec, kspec, bias_spec, mask_spec],
        out_specs=[qspec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, Hq, 1, sq_p), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, bias, mask_op)
    return o, lse


def _bwd(q, k, v, o, lse, do, bias, mask_bias, causal, scale, block_q,
         block_k, sq, sk):
    B, Hq, sq_p, D = q.shape
    sk_p = k.shape[2]
    nq, nk = sq_p // block_q, sk_p // block_k
    have_mask = mask_bias is not None
    mask_op = (mask_bias if have_mask
               else jnp.zeros((1, 1, 1, sk_p), jnp.float32))
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, :, None, :]
    kw = dict(scale=scale, causal=causal, sq=sq, sk=sk, block_q=block_q,
              block_k=block_k, have_mask=have_mask)
    sem4 = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))

    qspec, kspec, bias_spec, mask_spec, row_spec = _specs(
        B, Hq, bias.shape, mask_op.shape, block_q, block_k, D)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kw),
        grid=(B, Hq, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, row_spec, row_spec, bias_spec,
                  mask_spec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=sem4, interpret=_interpret(),
    )(q, k, v, do, lse, delta, bias, mask_op)

    qspec2, kspec2, bias_spec2, mask_spec2, row_spec2 = _specs(
        B, Hq, bias.shape, mask_op.shape, block_q, block_k, D, order="kq")
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **kw),
        grid=(B, Hq, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, row_spec2, row_spec2,
                  bias_spec2, mask_spec2],
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        compiler_params=sem4, interpret=_interpret(),
    )(q, k, v, do, lse, delta, bias, mask_op)

    # dbias: grid walks bias tiles; the (batch, head) broadcast-group
    # members are the innermost arbitrary dims, accumulated in scratch
    Bb, Hb = bias.shape[0], bias.shape[1]
    Gb, Gh = B // Bb, Hq // Hb
    mask_b = mask_op.shape[0]

    def full(spec_block, imap):
        return pl.BlockSpec(spec_block, imap)

    dbias = pl.pallas_call(
        functools.partial(_bwd_dbias_kernel, **kw, gb=Gb, gh=Gh),
        grid=(Bb, Hb, nq, nk, Gb, Gh),
        in_specs=[
            full((1, 1, block_q, D),
                 lambda b, h, i, j, g, e: (b * Gb + g, h * Gh + e, i, 0)),
            full((1, 1, block_k, D),
                 lambda b, h, i, j, g, e: (b * Gb + g, h * Gh + e, j, 0)),
            full((1, 1, block_k, D),
                 lambda b, h, i, j, g, e: (b * Gb + g, h * Gh + e, j, 0)),
            full((1, 1, block_q, D),
                 lambda b, h, i, j, g, e: (b * Gb + g, h * Gh + e, i, 0)),
            full((1, 1, 1, block_q),
                 lambda b, h, i, j, g, e: (b * Gb + g, h * Gh + e, 0, i)),
            full((1, 1, 1, block_q),
                 lambda b, h, i, j, g, e: (b * Gb + g, h * Gh + e, 0, i)),
            full((1, 1, block_q, block_k),
                 lambda b, h, i, j, g, e: (b, h, i, j)),
            full((1, 1, 1, block_k),
                 lambda b, h, i, j, g, e: ((b * Gb + g) // (B // mask_b),
                                           0, 0, j)),
        ],
        out_specs=full((1, 1, block_q, block_k),
                       lambda b, h, i, j, g, e: (b, h, i, j)),
        out_shape=jax.ShapeDtypeStruct(bias.shape, jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, block_k), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "parallel", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, bias, mask_op)
    return dq, dk, dv, dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_bias(q, k, v, bias, mask_bias, causal, scale, block_q, block_k,
                sq, sk):
    o, _ = _fwd(q, k, v, bias, mask_bias, causal, scale, block_q, block_k,
                sq, sk)
    return o


def _flash_bias_fwd(q, k, v, bias, mask_bias, causal, scale, block_q,
                    block_k, sq, sk):
    o, lse = _fwd(q, k, v, bias, mask_bias, causal, scale, block_q, block_k,
                  sq, sk)
    return o, (q, k, v, bias, mask_bias, o, lse)


def _flash_bias_bwd(causal, scale, block_q, block_k, sq, sk, res, do):
    q, k, v, bias, mask_bias, o, lse = res
    dq, dk, dv, dbias = _bwd(q, k, v, o, lse, do, bias, mask_bias, causal,
                             scale, block_q, block_k, sq, sk)
    dmask = None if mask_bias is None else jnp.zeros_like(mask_bias)
    return dq, dk, dv, dbias.astype(bias.dtype), dmask


_flash_bias.defvjp(_flash_bias_fwd, _flash_bias_bwd)


def flash_attention_bias(q, k, v, bias, mask_bias=None, causal=False,
                         softmax_scale=None, block_q=DEFAULT_BLOCK_Q,
                         block_k=DEFAULT_BLOCK_K):
    """[B, S, H, D] flash attention with a trainable additive bias.

    ``bias``: [Bb, Hb, Sq, Sk] with Bb | B and Hb | H — broadcast groups are
    *contiguous* runs of the batch/head axes (batch index b uses bias row
    b // (B//Bb); fold e.g. an MSA [B, N] batch as B·N with Bb = B).  Its
    gradient comes back at the same [Bb, Hb, Sq, Sk] shape, reduced in-kernel.

    ``mask_bias``: optional additive [Bm, 1, 1, Sk] with Bm | B (key
    validity mask; contiguous grouping b → b // (B//Bm), consistent with
    the bias); NON-differentiable on this path (zero cotangent) — mask
    biases are -inf-style constants.

    Differentiable in q, k, v, bias (custom VJP, flash recomputation).
    """
    B, sq, H, D = q.shape
    _, sk, Hk, _ = k.shape
    if Hk != H:
        raise ValueError("flash_attention_bias: GQA is not supported "
                         f"(q heads {H} != kv heads {Hk})")
    if bias.ndim != 4 or B % bias.shape[0] or H % bias.shape[1]:
        raise ValueError(f"bias shape {bias.shape} must be [Bb, Hb, Sq, Sk] "
                         f"with Bb | {B} and Hb | {H}")
    if bias.shape[2] != sq or bias.shape[3] != sk:
        raise ValueError(f"bias [..., {bias.shape[2]}, {bias.shape[3]}] must "
                         f"carry the full [Sq={sq}, Sk={sk}] score plane")
    scale = float(softmax_scale) if softmax_scale is not None else D**-0.5
    block_q = max(16, min(block_q, sq))
    block_k = max(16, min(block_k, sk))

    qt = _pad_to(_pad_to(q.transpose(0, 2, 1, 3), 2, block_q), 3, 128)
    kt = _pad_to(_pad_to(k.transpose(0, 2, 1, 3), 2, block_k), 3, 128)
    vt = _pad_to(_pad_to(v.transpose(0, 2, 1, 3), 2, block_k), 3, 128)
    bt = _pad_to(_pad_to(bias, 2, block_q), 3, block_k)
    mt = None
    if mask_bias is not None:
        if mask_bias.ndim != 4 or mask_bias.shape[1:3] != (1, 1) or \
                B % mask_bias.shape[0]:
            raise ValueError(f"mask_bias shape {mask_bias.shape} must be "
                             f"[Bm, 1, 1, Sk] with Bm | {B}")
        mt = _pad_to(jax.lax.stop_gradient(
            mask_bias.astype(jnp.float32)), 3, block_k)
    o = _flash_bias(qt, kt, vt, bt, mt, bool(causal), scale, block_q,
                    block_k, sq, sk)
    return o[:, :, :sq, :D].transpose(0, 2, 1, 3)
