"""Paged (blocked-KV) attention, Pallas TPU — the FastGen blocked-flash
analog (reference ``inference/v2/kernels/ragged_ops/blocked_flash`` +
``linear_blocked_kv_rotary``).

One grid row per ragged-batch token; the token's KV *pages* are streamed
through VMEM in block-table order using scalar-prefetched indices (the
``PrefetchScalarGridSpec`` pattern: the block index map reads the table, so
the pipeline DMAs exactly the pages this token owns), with the online-softmax
state in VMEM scratch.  GQA is expressed in the index math (no repeated KV).

The XLA fallback (``inference/v2/ragged_forward._paged_attention``) computes
the same math by gather; this kernel replaces it on TPU where the gather's
HBM blowup ([T, max_ctx, ...]) matters.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")


from ._common import interpret_mode as _interpret


def paged_attention(q, k_cache, v_cache, tables_t, positions,
                    block_size=None, window=0):
    """q: [T, H, Dh]; caches: [num_blocks, bs, Hkv, Dh];
    tables_t: [T, maxb] int32; positions: [T] int32 → [T, H, Dh].

    One token per grid row — exactly the atom-tiled kernel with atom=1
    (one shared online-softmax implementation; see _atom_kernel)."""
    return paged_attention_atoms(q, k_cache, v_cache, tables_t,
                                 positions, 1, window=window)


# ------------------------------------------------------- atom (prefill) path
def _atom_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref,
                 m_ref, l_ref, *, block_size, scale, groups, atom,
                 window):
    """Like :func:`_kernel` but one grid row covers ``atom`` consecutive
    buffer tokens OF THE SAME SEQUENCE (the batch builder guarantees the
    alignment; intra-atom pad rows produce discarded outputs).  The q tile
    becomes [Hkv, atom*g, Dh], so each kv-head dot has ``atom*g`` MXU rows
    instead of ``g`` — the reference's atom_builder idea
    (``inference/v2/kernels/ragged_ops/atom_builder``) expressed as tiling.
    """
    i, j = pl.program_id(0), pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    k_start = j * block_size
    # positions are consecutive within a run; pads carry pos 0, so the last
    # real row's position is the max → block-liveness bound for the tile
    pos_tile = jnp.asarray([pos_ref[i * atom + r] for r in range(atom)],
                           dtype=jnp.int32)            # [atom]
    max_pos = jnp.max(pos_tile)
    live = k_start <= max_pos
    if window:
        # blocks entirely older than the oldest row's window are dead;
        # pad rows carry pos 0, which only loosens the bound (correct)
        live = jnp.logical_and(
            live, k_start + block_size - 1 > jnp.min(pos_tile) - window)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)               # [atom, H, Dh]
        k = k_ref[0].astype(jnp.float32)               # [bs, Hkv, Dh]
        v = v_ref[0].astype(jnp.float32)
        A, H, Dh = q.shape
        bs, Hkv, _ = k.shape
        # [A, H, Dh] → [Hkv, A*g, Dh]; row order within a kv head: (a, g)
        qg = q.reshape(A, Hkv, groups, Dh).transpose(1, 0, 2, 3) \
              .reshape(Hkv, A * groups, Dh)
        s = jnp.einsum("kmd,bkd->kmb", qg, k,
                       preferred_element_type=jnp.float32) * scale
        col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        pos_rows = jnp.broadcast_to(pos_tile[:, None],
                                    (A, groups)).reshape(1, A * groups, 1)
        mask = col <= pos_rows
        if window:  # sliding window: only the last `window` positions
            mask = jnp.logical_and(mask, col > pos_rows - window)
        s = jnp.where(mask, s, _NEG_INF)

        M = Hkv * A * groups
        s_f = s.reshape(M, bs)
        m_prev = m_ref[:, :1]                          # [M, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s_f, axis=1, keepdims=True))
        m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        p = jnp.exp(s_f - m_safe)
        p = jnp.where(s_f == _NEG_INF, 0.0, p)
        alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - m_safe))
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv = jnp.einsum("kmb,bkd->kmd", p.reshape(Hkv, A * groups, bs), v,
                        preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(M, Dh)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nb - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_ref[:] / l_safe                      # [Hkv*A*g, Dh]
        _, A, H, Dh = o_ref.shape
        Hkv = H // groups
        out = out.reshape(Hkv, A, groups, Dh).transpose(1, 0, 2, 3) \
                 .reshape(A, H, Dh)
        o_ref[0] = out.astype(o_ref.dtype)


def paged_attention_atoms(q, k_cache, v_cache, tables_t, positions,
                          atom, block_size=None, window=0):
    """Atom-tiled variant for prefill regions: q rows [T, H, Dh] where every
    aligned run of ``atom`` rows shares one sequence (pads allowed).  Page
    streaming uses the FIRST row's block table; per-row position masking
    gives each token its causal view.  T must be a multiple of ``atom``."""
    T, H, Dh = q.shape
    if T % atom:
        raise ValueError(f"token count {T} not a multiple of atom {atom}")
    nb_total, bs, Hkv, _ = k_cache.shape
    maxb = tables_t.shape[1]
    groups = H // Hkv
    scale = Dh**-0.5
    n_atoms = T // atom

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_atoms, maxb),
        in_specs=[
            pl.BlockSpec((1, atom, H, Dh), lambda i, j, tb, ps: (i, 0, 0, 0)),
            pl.BlockSpec((1, bs, Hkv, Dh),
                         lambda i, j, tb, ps: (tb[i * atom, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, Hkv, Dh),
                         lambda i, j, tb, ps: (tb[i * atom, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, atom, H, Dh),
                               lambda i, j, tb, ps: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hkv * atom * groups, Dh), jnp.float32),
            pltpu.VMEM((Hkv * atom * groups, 128), jnp.float32),
            pltpu.VMEM((Hkv * atom * groups, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_atom_kernel, block_size=bs, scale=scale,
                          groups=groups, atom=atom, window=int(window)),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_atoms, atom, H, Dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(tables_t, positions, q.reshape(n_atoms, atom, H, Dh),
      k_cache, v_cache).reshape(T, H, Dh)
