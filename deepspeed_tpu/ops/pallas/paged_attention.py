"""Paged (blocked-KV) attention, Pallas TPU — the FastGen blocked-flash
analog (reference ``inference/v2/kernels/ragged_ops/blocked_flash`` +
``linear_blocked_kv_rotary``).

One grid row per ragged-batch token; the token's KV *pages* are streamed
through VMEM in block-table order using scalar-prefetched indices (the
``PrefetchScalarGridSpec`` pattern: the block index map reads the table, so
the pipeline DMAs exactly the pages this token owns), with the online-softmax
state in VMEM scratch.  GQA is expressed in the index math (no repeated KV).

The XLA fallback (``inference/v2/ragged_forward._paged_attention``) computes
the same math by gather; this kernel replaces it on TPU where the gather's
HBM blowup ([T, max_ctx, ...]) matters.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")


from ._common import interpret_mode as _interpret


def _kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
            l_ref, *, block_size, scale, groups):
    t, j = pl.program_id(0), pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    my_pos = pos_ref[t]
    k_start = j * block_size

    @pl.when(k_start <= my_pos)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [H, Dh]
        k = k_ref[0].astype(jnp.float32)          # [bs, Hkv, Dh]
        v = v_ref[0].astype(jnp.float32)
        H, Dh = q.shape
        bs, Hkv, _ = k.shape
        qg = q.reshape(Hkv, groups, Dh)
        # scores [Hkv, g, bs] — per-kv-head MXU dots, no repeated KV
        s = jnp.einsum("kgd,bkd->kgb", qg, k,
                       preferred_element_type=jnp.float32) * scale
        col = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        mask = col <= my_pos
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]                       # [H, 1]
        s_f = s.reshape(H, bs)
        m_new = jnp.maximum(m_prev, jnp.max(s_f, axis=1, keepdims=True))
        m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        p = jnp.exp(s_f - m_safe)
        p = jnp.where(s_f == _NEG_INF, 0.0, p)
        alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - m_safe))
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        pv = jnp.einsum("kgb,bkd->kgd", p.reshape(Hkv, groups, bs), v,
                        preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(H, Dh)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nb - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def paged_attention(q, k_cache, v_cache, tables_t, positions,
                    block_size=None):
    """q: [T, H, Dh]; caches: [num_blocks, bs, Hkv, Dh];
    tables_t: [T, maxb] int32; positions: [T] int32 → [T, H, Dh]."""
    T, H, Dh = q.shape
    nb_total, bs, Hkv, _ = k_cache.shape
    maxb = tables_t.shape[1]
    groups = H // Hkv
    scale = Dh**-0.5

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T, maxb),
        in_specs=[
            pl.BlockSpec((1, H, Dh), lambda t, j, tb, ps: (t, 0, 0)),
            pl.BlockSpec((1, bs, Hkv, Dh),
                         lambda t, j, tb, ps: (tb[t, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, Hkv, Dh),
                         lambda t, j, tb, ps: (tb[t, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, Dh), lambda t, j, tb, ps: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, Dh), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, block_size=bs, scale=scale,
                          groups=groups),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, H, Dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(tables_t, positions, q, k_cache, v_cache)
