"""Layout-skip block-sparse flash attention, Pallas TPU.

The reference implements block-sparse attention as Triton ``sdd``/``dsd``
block matmuls + a block-sparse softmax (``ops/sparse_attention/matmul.py``,
``softmax.py``).  The TPU formulation here streams, for every q block, ONLY
its layout-allowed k/v blocks through VMEM using scalar-prefetched block
indices (the same ``PrefetchScalarGridSpec`` trick as
``paged_attention.py``): the grid's inner dim walks the row's live-block
list, so both FLOPs and HBM traffic are proportional to the layout's
populated blocks — padded to the max row population, never to nk.

vs the XLA gather formulation (``sparse_attention.py``): the gather
materializes a [B, nq, maxk, block, D] copy of the gathered K/V in HBM;
this kernel reads each needed block exactly once per q-row directly from
the original tensors and keeps the online-softmax state in VMEM.

Backward: ``custom_vjp`` whose backward differentiates the (numerically
identical) gather formulation — also nnz-proportional, at the cost of the
transient gather buffers during the backward pass only.

Perf note: kernel tiles equal the LAYOUT block size; layouts built with
block ≥ 64 tile the MXU well (16-wide layouts work but underfill it).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import interpret_mode as _interpret
from .flash_attention import _NEG_INF, _pad_to, _score_mask


def _kernel(idx_ref, valid_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
            l_ref, *, scale, causal, block, sq):
    ih, iq, j = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    nkslots = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(valid_ref[ih, iq, j] == 1)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_start = iq * block
        k_start = idx_ref[ih, iq, j] * block
        mask = _score_mask(q_start, k_start, causal, sq, sq, block, block)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        p = jnp.where(mask, jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - m_safe))
        l_ref[:] = jnp.broadcast_to(
            alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True),
            l_ref.shape)
        v = v_ref[0, 0].astype(jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == nkslots - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _fwd(q, k, v, idx, valid, block, causal, scale, sq):
    """q/k/v padded [B, H, S_p, D_p]; idx/valid [H, nq, maxk] int32."""
    B, H, _, D = q.shape  # S is layout-aligned already; only D is padded
    nq, maxk = idx.shape[1], idx.shape[2]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nq, maxk),
        in_specs=[
            pl.BlockSpec((1, 1, block, D),
                         lambda b, h, i, j, ix, vd: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block, D),
                         lambda b, h, i, j, ix, vd: (b, h, ix[h, i, j], 0)),
            pl.BlockSpec((1, 1, block, D),
                         lambda b, h, i, j, ix, vd: (b, h, ix[h, i, j], 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block, D),
                               lambda b, h, i, j, ix, vd: (b, h, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block, D), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, scale=scale, causal=causal, block=block,
                          sq=sq),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(idx, valid, q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _bs_flash(q, k, v, idx, valid, block, causal, scale, sq, gather_ref):
    return _fwd(q, k, v, idx, valid, block, causal, scale, sq)


def _bs_fwd(q, k, v, idx, valid, block, causal, scale, sq, gather_ref):
    return _fwd(q, k, v, idx, valid, block, causal, scale, sq), (q, k, v)


def _bs_bwd(block, causal, scale, sq, gather_ref, res, do):
    """Backward = AD of the gather formulation (same math, differentiable,
    nnz-proportional); gather buffers exist only during this pass."""
    q, k, v = res
    _, vjp = jax.vjp(gather_ref, q, k, v)
    dq, dk, dv = vjp(do)
    return dq, dk, dv, None, None


_bs_flash.defvjp(_bs_fwd, _bs_bwd)


def block_sparse_flash_attention(q, k, v, layout, block, causal=False,
                                 scale=None):
    """[B, S, H, D] block-sparse attention streaming only the layout's live
    blocks (layout: [H or 1, nq, nk] bool).  Differentiable; numerics match
    ``sparse_attention.sparse_attention`` (the gather formulation) exactly.
    S must be a multiple of ``block`` (sparsity layouts already are)."""
    from ..sparse_attention.sparse_self_attention import (
        layout_gather_tables, sparse_attention)

    B, S, H, D = q.shape
    if S % block:
        raise ValueError(f"S={S} not a multiple of layout block {block}")
    scale_v = scale if scale is not None else D ** -0.5
    layout, idx, valid = layout_gather_tables(layout, H)
    valid = valid.astype("int32")

    qt = _pad_to(q.transpose(0, 2, 1, 3), 3, 128)
    kt = _pad_to(k.transpose(0, 2, 1, 3), 3, 128)
    vt = _pad_to(v.transpose(0, 2, 1, 3), 3, 128)

    def gather_ref(qp, kp, vp):
        """The gather formulation on the padded operands (backward path)."""
        qs = qp.transpose(0, 2, 1, 3)[..., :D]
        ks = kp.transpose(0, 2, 1, 3)[..., :D]
        vs = vp.transpose(0, 2, 1, 3)[..., :D]
        out = sparse_attention(qs, ks, vs, layout, block, causal=causal,
                               scale=scale_v)
        return _pad_to(out.transpose(0, 2, 1, 3), 3, 128)

    o = _bs_flash(qt, kt, vt, jnp.asarray(idx), jnp.asarray(valid), block,
                  bool(causal), scale_v, S, gather_ref)
    return o[..., :D].transpose(0, 2, 1, 3)
