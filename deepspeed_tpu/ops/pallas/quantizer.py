"""Blockwise integer (de)quantization kernels (Pallas TPU).

TPU answer to ``csrc/quantization/{quantize,dequantize,quant_reduce}.cu``:
symmetric per-group int8/int4 quantization used by

  * ZeRO++ qwZ — quantized weight all-gather (``runtime/zero/zeropp``);
  * ZeRO++ qgZ — quantize → all-to-all → dequant-reduce gradient path;
  * weight-only inference quantization (``inference/quantization``).

No swizzle kernel is needed: the reference's ``swizzled_quantize.cu`` exists
to reorder data for NCCL's hierarchical all-to-all; on TPU the hierarchy is
expressed as mesh-axis-factored collectives, so the layout is already right.

Groups are rows of a (num_groups, group_size) view; scales are per-group
absmax/qmax (symmetric, matching the reference's default quantization mode).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


from ._common import interpret_mode as _interpret


def _quant_kernel(x_ref, q_ref, s_ref, *, qmax):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / qmax)
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = jnp.broadcast_to(scale, s_ref.shape)


def _dequant_kernel(q_ref, s_ref, out_ref):
    out_ref[:] = (q_ref[:].astype(jnp.float32) *
                  s_ref[:, :1]).astype(out_ref.dtype)


def _pick_block(group_size):
    """Row-block sized to keep the VMEM working set ≈1 MiB (power-of-two,
    8..512)."""
    block = 512
    while block > 8 and block * group_size * 4 > (1 << 20):
        block //= 2
    return block


def _group_view(x, group_size, block):
    """Flatten → zero-pad → (groups, group_size), with the group count padded
    to a multiple of ``block`` so the pallas grid covers every row."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    groups = -(-n // group_size)
    groups_pad = groups + (-groups) % 8
    if groups_pad > block:
        groups_pad += (-groups_pad) % block
    flat = jnp.pad(flat, (0, groups_pad * group_size - n))
    return flat.reshape(groups_pad, group_size), n, groups


def quantize_blockwise(x, num_bits=8, group_size=2048, use_pallas=None):
    """Symmetric per-group quantization.

    Returns ``(q_int8, scales_f32, meta)`` where ``meta = (orig_shape,
    orig_dtype, valid_groups)``; int4 values occupy int8 storage (range ±7),
    packing is the transport layer's concern.
    """
    group_size = max(_LANES, group_size - group_size % _LANES)
    qmax = 127.0 if num_bits == 8 else float(2**(num_bits - 1) - 1)
    tiles, n, groups = _group_view(x, group_size, _pick_block(group_size))
    meta = (x.shape, x.dtype, groups)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if not use_pallas:
        xf = tiles.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
        scale = jnp.where(absmax == 0.0, 1.0, absmax / qmax)
        q = jnp.clip(jnp.round(xf / scale), -qmax, qmax).astype(jnp.int8)
        return q, scale[:, 0], meta

    rows = tiles.shape[0]
    block = min(_pick_block(group_size), rows)
    spec = pl.BlockSpec((block, group_size), lambda i: (i, 0))
    s_spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
    q, s = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=qmax),
        grid=(rows // block, ),
        in_specs=[spec],
        out_specs=[spec, s_spec],
        out_shape=[
            jax.ShapeDtypeStruct(tiles.shape, jnp.int8),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(tiles)
    return q, s[:, 0], meta


def dequantize_blockwise(q, scales, meta, use_pallas=None):
    """Inverse of :func:`quantize_blockwise`."""
    shape, dtype, _ = meta
    n = 1
    for d in shape:
        n *= d
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if not use_pallas:
        out = q.astype(jnp.float32) * scales[:, None]
    else:
        rows, group_size = q.shape
        block = min(_pick_block(group_size), rows)
        spec = pl.BlockSpec((block, group_size), lambda i: (i, 0))
        s_spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
        s_l = jnp.broadcast_to(scales[:, None], (rows, _LANES))
        out = pl.pallas_call(
            _dequant_kernel,
            grid=(rows // block, ),
            in_specs=[spec, s_spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct(q.shape, jnp.float32),
            interpret=_interpret(),
        )(q, s_l)
    return out.reshape(-1)[:n].reshape(shape).astype(dtype)
