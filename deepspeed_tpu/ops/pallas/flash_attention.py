"""Blockwise online-softmax (flash) attention, Pallas TPU.

TPU-native re-design of the reference's attention kernels
(``csrc/transformer/inference/csrc/softmax.cu`` + the FastGen blocked flash,
``inference/v2/kernels/ragged_ops/blocked_flash``): one fused kernel that
streams K/V blocks through VMEM, keeping the running max/sum (online softmax,
the same recurrence FPDT uses at chunk granularity —
``deepspeed/sequence/fpdt_layer.py:58 update_out_and_lse``) in VMEM scratch so
the S×S score matrix never exists in HBM.

Layout: [B, H, S, D] inside the kernel (callers use [B, S, H, D]; the public
wrapper transposes).  Q-heads may be a multiple of KV-heads (GQA/MQA): K/V
blocks are fetched per KV-head via the BlockSpec index map — no materialized
`repeat`, so HBM traffic stays proportional to the KV size.

Backward is the standard two-kernel flash recomputation (dq; dk+dv) behind a
``jax.custom_vjp``.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Block sizes swept on the real v5e chip (536M-param Llama bench, S=2048,
# bf16): 128/128 → 0.364 MFU, 256/256 → 0.509, 256/512 → 0.534,
# 512/256 → 0.531, 512/512 → 0.581.  Large tiles win: fewer grid steps and
# better MXU occupancy beat the extra VMEM (~1.5 MB total at D=128).
# Override per-run with DS_TPU_FLASH_BLOCK_Q/K.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = float("-inf")
_DEAD_ROW_LSE = -1e30  # finite lse sentinel for fully-masked rows


from ._common import interpret_mode as _interpret


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    rem = (-size) % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


def _eye(n, dtype):
    return (jax.lax.broadcasted_iota(jnp.int32, (n, n), 0) ==
            jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)).astype(dtype)


def _col_to_row(col):
    """(n, 1) → (1, n) via an MXU identity contraction — a Mosaic-safe way to
    move per-row scalars from sublanes into lanes (cheap: n² MACs)."""
    return jax.lax.dot_general(col, _eye(col.shape[0], col.dtype),
                               (((0, ), (0, )), ((), ())),
                               preferred_element_type=jnp.float32)


def _row_to_col(row):
    """(1, n) → (n, 1) via an MXU identity contraction."""
    return jax.lax.dot_general(_eye(row.shape[1], row.dtype), row,
                               (((1, ), (1, )), ((), ())),
                               preferred_element_type=jnp.float32)


def _score_mask(q_start, k_start, causal, sq, sk, block_q, block_k,
                window=0):
    """Validity mask for one (block_q, block_k) score tile.  ``sq``/``sk`` are
    the *unpadded* lengths, so the zero-padded K tail is always excluded.
    ``window`` > 0 additionally limits each query to the last ``window`` keys
    (Mistral sliding window; requires causal)."""
    col = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    mask = col < sk
    if causal:
        row = q_start + jax.lax.broadcasted_iota(jnp.int32,
                                                 (block_q, block_k), 0)
        mask = jnp.logical_and(mask, row + (sk - sq) >= col)
        if window:
            mask = jnp.logical_and(mask, col > row + (sk - sq) - window)
    return mask


def _alibi_bias(s, slopes_ref, h, k_start, alibi):
    """Softmax-invariant ALiBi: + slope_h * absolute key position.  ONE
    definition shared by the forward and both backward kernels so the
    recomputed probabilities can never diverge from the forward pass."""
    if not alibi:
        return s
    col = k_start + jax.lax.broadcasted_iota(jnp.float32, s.shape, 1)
    return s + slopes_ref[h, 0] * col


def _block_live(q_start, k_start, causal, sq, sk, block_q, block_k=None,
                window=0):
    """Whether this K block contributes at all (static-shape early-out).
    With a sliding window, K blocks entirely older than the newest query's
    window are dead — the block-skip that makes window cost O(S·W)."""
    live = k_start < sk
    if causal:
        live = jnp.logical_and(live,
                               k_start <= q_start + block_q - 1 + (sk - sq))
        if window:
            live = jnp.logical_and(
                live, k_start + block_k - 1 > q_start + (sk - sq) - window)
    return live


# --------------------------------------------------------------------- fwd
def _fwd_kernel(q_ref, k_ref, v_ref, slopes_ref, o_ref, lse_ref, acc_ref,
                m_ref, l_ref, *, scale, causal, sq, sk, block_q, block_k,
                window, alibi):
    ih = pl.program_id(1)
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start, k_start = iq * block_q, ik * block_k

    @pl.when(_block_live(q_start, k_start, causal, sq, sk, block_q,
                         block_k, window))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _alibi_bias(s, slopes_ref, ih, k_start, alibi)
        mask = _score_mask(q_start, k_start, causal, sq, sk, block_q, block_k,
                           window)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Rows with every position masked (padded Q tail) keep m=-inf; guard
        # the exp so they stay 0 rather than nan.
        m_safe = jnp.where(m_new == _NEG_INF, 0.0, m_new)
        p = jnp.where(mask, jnp.exp(s - m_safe), 0.0)
        alpha = jnp.where(m_prev == _NEG_INF, 0.0, jnp.exp(m_prev - m_safe))
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        m = m_ref[:, :1]
        # Dead (fully-masked) rows get a finite -1e30 sentinel, not -inf: the
        # identity contraction below computes sum_i lse[i]·eye[i,j], and
        # (-inf)·0 = NaN would poison every row of the packed block.  The
        # backward needs no special-casing — exp(s − (−1e30)) at the dead
        # rows' masked positions is exp(−inf) = 0.
        lse = jnp.where(m == _NEG_INF, _DEAD_ROW_LSE, m + jnp.log(l_safe))
        # lse output is packed [B,H,1,S] (S in lanes, unit sublane dim so the
        # Mosaic block rule "dim -2 divisible by 8 OR equal to the array dim"
        # holds) — no 128-lane inflation
        lse_ref[0, 0] = _col_to_row(lse)


def _fwd(q, k, v, slopes, causal, scale, block_q, block_k, sq, sk,
         window, alibi):
    """Core on padded [B,H,S,D] inputs; sq/sk are the unpadded lengths."""
    B, Hq, sq_p, D = q.shape
    _, Hkv, sk_p, _ = k.shape
    nq, nk = sq_p // block_q, sk_p // block_k
    kv_head = lambda h: (h * Hkv) // Hq

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               sq=sq, sk=sk, block_q=block_q,
                               block_k=block_k, window=window,
                               alibi=alibi)
    o, lse = pl.pallas_call(
        kernel,
        grid=(B, Hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), j, 0)),
            pl.BlockSpec((Hq, 1), lambda b, h, i, j: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, block_q), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, sq_p, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, 1, sq_p), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, slopes)
    return o, lse


# --------------------------------------------------------------------- bwd
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   slopes_ref, dq_ref, acc_ref, *, scale, causal, sq, sk,
                   block_q, block_k, window, alibi):
    ih = pl.program_id(1)
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start, k_start = iq * block_q, ik * block_k

    @pl.when(_block_live(q_start, k_start, causal, sq, sk, block_q,
                         block_k, window))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = _row_to_col(lse_ref[0, 0])   # packed [1,bq] lanes → [bq,1]
        delta = _row_to_col(delta_ref[0, 0])
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _alibi_bias(s, slopes_ref, ih, k_start, alibi)
        mask = _score_mask(q_start, k_start, causal, sq, sk, block_q, block_k,
                           window)
        # dead rows carry the finite _DEAD_ROW_LSE sentinel; their positions
        # are all masked, so the select discards whatever exp produced
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        acc_ref[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    slopes_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale,
                    causal, sq, sk, block_q, block_k, window, alibi):
    ih = pl.program_id(1)
    ik, iq = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start, k_start = iq * block_q, ik * block_k

    @pl.when(_block_live(q_start, k_start, causal, sq, sk, block_q,
                         block_k, window))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = _row_to_col(lse_ref[0, 0])   # packed [1,bq] lanes → [bq,1]
        delta = _row_to_col(delta_ref[0, 0])
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = _alibi_bias(s, slopes_ref, ih, k_start, alibi)
        mask = _score_mask(q_start, k_start, causal, sq, sk, block_q, block_k,
                           window)
        # dead rows carry the finite _DEAD_ROW_LSE sentinel; their positions
        # are all masked, so the select discards whatever exp produced
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        # dv += pᵀ·do ; ds = p∘(do·vᵀ − delta) ; dk += dsᵀ·q
        dv_acc[:] += jax.lax.dot_general(p, do, (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, slopes, causal, scale, block_q, block_k,
         sq, sk, window, alibi):
    B, Hq, sq_p, D = q.shape
    _, Hkv, sk_p, _ = k.shape
    nq, nk = sq_p // block_q, sk_p // block_k
    kv_head = lambda h: (h * Hkv) // Hq
    # Per-row scalars stay packed [B,H,1,S] (S in lanes, unit sublane) — the
    # kernels unpack a (1, block_q) row to a (block_q, 1) column with an MXU
    # identity contraction instead of hauling 128 duplicated lanes through
    # HBM.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, :, None, :]

    semantics = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, sq=sq,
                          sk=sk, block_q=block_q, block_k=block_k,
                          window=window, alibi=alibi),
        grid=(B, Hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), j, 0)),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, block_q), lambda b, h, i, j: (b, h, 0, i)),
            pl.BlockSpec((1, 1, 1, block_q), lambda b, h, i, j: (b, h, 0, i)),
            pl.BlockSpec((Hq, 1), lambda b, h, i, j: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=semantics,
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, slopes)

    # dk/dv are produced per *query* head ([B,Hq,Sk,D]) and group-summed to
    # KV heads afterwards — the GQA head fan-in.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, sq=sq,
                          sk=sk, block_q=block_q, block_k=block_k,
                          window=window, alibi=alibi),
        grid=(B, Hq, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), i, 0)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), i, 0)),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, 1, block_q), lambda b, h, i, j: (b, h, 0, j)),
            pl.BlockSpec((1, 1, 1, block_q), lambda b, h, i, j: (b, h, 0, j)),
            pl.BlockSpec((Hq, 1), lambda b, h, i, j: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, sk_p, D), k.dtype),
            jax.ShapeDtypeStruct((B, Hq, sk_p, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=semantics,
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, slopes)
    if Hq != Hkv:
        g = Hq // Hkv
        dk = dk.reshape(B, Hkv, g, sk_p, D).sum(axis=2).astype(k.dtype)
        dv = dv.reshape(B, Hkv, g, sk_p, D).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


# ------------------------------------------------------------------ public
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, slopes, causal, scale, block_q, block_k, sq, sk, window,
           alibi):
    o, _ = _fwd(q, k, v, slopes, causal, scale, block_q, block_k, sq, sk,
                window, alibi)
    return o


def _flash_fwd(q, k, v, slopes, causal, scale, block_q, block_k, sq, sk,
               window, alibi):
    o, lse = _fwd(q, k, v, slopes, causal, scale, block_q, block_k, sq, sk,
                  window, alibi)
    return o, (q, k, v, slopes, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, sq, sk, window, alibi, res,
               do):
    q, k, v, slopes, o, lse = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, slopes, causal, scale, block_q,
                      block_k, sq, sk, window, alibi)
    return dq, dk, dv, jnp.zeros_like(slopes)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=True, softmax_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    window=0, alibi_slopes=None):
    """[B, S, H, D] flash attention with GQA (Hkv | Hq) support.

    Differentiable (custom VJP with flash recomputation).  S and D need not be
    block-aligned; inputs are zero-padded and masked internally.  ``window``
    > 0 restricts each query to the last ``window`` keys (Mistral sliding
    window) with dead K blocks skipped — requires ``causal``.
    """
    B, sq, Hq, D = q.shape
    _, sk, Hkv, _ = k.shape
    if Hq % Hkv:
        raise ValueError(f"q heads {Hq} not a multiple of kv heads {Hkv}")
    if window and not causal:
        raise ValueError("sliding window requires causal attention")
    scale = float(softmax_scale) if softmax_scale is not None else D**-0.5
    block_q = max(16, min(block_q, sq))
    block_k = max(16, min(block_k, sk))

    qt = _pad_to(_pad_to(q.transpose(0, 2, 1, 3), 2, block_q), 3, 128)
    kt = _pad_to(_pad_to(k.transpose(0, 2, 1, 3), 2, block_k), 3, 128)
    vt = _pad_to(_pad_to(v.transpose(0, 2, 1, 3), 2, block_k), 3, 128)
    alibi = alibi_slopes is not None
    # slopes are positional constants (ALiBi), not trainable parameters —
    # stop_gradient makes that explicit and keeps TPU/XLA paths consistent
    slopes = (jax.lax.stop_gradient(
        jnp.asarray(alibi_slopes, jnp.float32).reshape(Hq, 1))
        if alibi else jnp.zeros((Hq, 1), jnp.float32))
    o = _flash(qt, kt, vt, slopes, bool(causal), scale, block_q, block_k,
               sq, sk, int(window), alibi)
    return o[:, :, :sq, :D].transpose(0, 2, 1, 3)
