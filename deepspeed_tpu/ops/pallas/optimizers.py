"""Fused optimizer update kernels (Pallas TPU).

TPU answer to the reference's multi-tensor-apply CUDA optimizers
(``csrc/adam/multi_tensor_adam.cu``, ``csrc/lion/multi_tensor_lion.cu``,
``csrc/lamb/fused_lamb_cuda_kernel.cu``): one elementwise kernel that reads
the fp32 master weight + moments + (bf16) gradient and writes the updated
master, moments, and the re-cast bf16 model weight in a single pass over HBM —
the "interleaved master-weight cast + update" that XLA sometimes splits into
two passes.

Each leaf is processed independently (XLA fuses across leaves at the jit
level; there is no multi-tensor launch-overhead problem on TPU).  Arrays are
flattened and tiled (rows, 128); hyperparameters ride in SMEM.

LAMB is two-phase, like the reference kernel: phase 1 computes the Adam-style
update and per-tensor ‖p‖²,‖u‖² partial sums; the trust ratio is formed on the
host XLA graph; phase 2 applies ``p -= lr·ratio·u``.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_BLOCK_ROWS = 512  # 512×128 f32 = 256 KiB per buffer


from ._common import interpret_mode as _interpret


def _to_tiles(x):
    """Flatten → zero-pad → (rows, 128). Returns (tiles, orig_size).

    Rows are padded to a multiple of the grid block so ``rows // block``
    covers the whole array (zero padding is a fixed point of every update
    rule here: g=m=v=0 ⇒ step 0)."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    rows = max(8, -(-n // _LANES))
    rows += (-rows) % 8
    if rows > _BLOCK_ROWS:
        rows += (-rows) % _BLOCK_ROWS
    flat = jnp.pad(flat, (0, rows * _LANES - n))
    return flat.reshape(rows, _LANES), n


def _from_tiles(tiles, n, shape, dtype):
    return tiles.reshape(-1)[:n].reshape(shape).astype(dtype)


def _row_spec(rows):
    block = min(_BLOCK_ROWS, rows)
    return block, pl.BlockSpec((block, _LANES), lambda i: (i, 0))


# ---------------------------------------------------------------- adam
def _adam_kernel(h_ref, g_ref, p_ref, m_ref, v_ref, po_ref, mo_ref, vo_ref,
                 bf_ref, *, adam_w_mode):
    lr, b1, b2, eps, wd, c1, c2 = (h_ref[0, i] for i in range(7))
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:]
    if not adam_w_mode:
        g = g + wd * p
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    step = (m / c1) / (jnp.sqrt(v / c2) + eps)
    if adam_w_mode:
        step = step + wd * p
    p_new = p - lr * step
    po_ref[:] = p_new
    mo_ref[:] = m
    vo_ref[:] = v
    bf_ref[:] = p_new.astype(bf_ref.dtype)


def fused_adam_step(grad, master, m, v, *, lr, beta1, beta2, eps,
                    weight_decay, count, adam_w_mode=True,
                    bias_correction=True, out_dtype=jnp.bfloat16):
    """One fused Adam(W) update on a single leaf.

    Returns ``(param_out_dtype, master_f32, m_f32, v_f32)``.  ``count`` is the
    1-based step (traced scalar ok).
    """
    gt, n = _to_tiles(grad)
    pt, _ = _to_tiles(master.astype(jnp.float32))
    mt, _ = _to_tiles(m)
    vt, _ = _to_tiles(v)
    rows = gt.shape[0]
    cf = jnp.float32(count)
    c1 = 1.0 - jnp.float32(beta1)**cf if bias_correction else jnp.float32(1)
    c2 = 1.0 - jnp.float32(beta2)**cf if bias_correction else jnp.float32(1)
    hyper = jnp.stack([
        jnp.float32(lr), jnp.float32(beta1), jnp.float32(beta2),
        jnp.float32(eps), jnp.float32(weight_decay), c1, c2
    ]).reshape(1, 7)
    block, spec = _row_spec(rows)
    out = pl.pallas_call(
        functools.partial(_adam_kernel, adam_w_mode=adam_w_mode),
        grid=(rows // block, ),
        in_specs=[
            pl.BlockSpec((1, 7), lambda i: (0, 0), memory_space=pltpu.SMEM),
            spec, spec, spec, spec
        ],
        out_specs=[spec, spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct(gt.shape, jnp.float32),
            jax.ShapeDtypeStruct(gt.shape, jnp.float32),
            jax.ShapeDtypeStruct(gt.shape, jnp.float32),
            jax.ShapeDtypeStruct(gt.shape, jnp.dtype(out_dtype)),
        ],
        input_output_aliases={2: 0, 3: 1, 4: 2},
        interpret=_interpret(),
    )(hyper, gt, pt, mt, vt)
    p_new, m_new, v_new, bf16 = out
    shape = grad.shape
    return (_from_tiles(bf16, n, shape, out_dtype),
            _from_tiles(p_new, n, shape, jnp.float32),
            _from_tiles(m_new, n, shape, jnp.float32),
            _from_tiles(v_new, n, shape, jnp.float32))


# ---------------------------------------------------------------- lion
def _lion_kernel(h_ref, g_ref, p_ref, m_ref, po_ref, mo_ref, bf_ref):
    lr, b1, b2, wd = (h_ref[0, i] for i in range(4))
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:]
    update = jnp.sign(b1 * m_ref[:] + (1.0 - b1) * g)
    p_new = p - lr * (update + wd * p)
    po_ref[:] = p_new
    mo_ref[:] = b2 * m_ref[:] + (1.0 - b2) * g
    bf_ref[:] = p_new.astype(bf_ref.dtype)


def fused_lion_step(grad, master, m, *, lr, beta1, beta2, weight_decay,
                    out_dtype=jnp.bfloat16):
    """One fused Lion update (reference ``csrc/lion``).  Returns
    ``(param_out_dtype, master_f32, m_f32)``."""
    gt, n = _to_tiles(grad)
    pt, _ = _to_tiles(master.astype(jnp.float32))
    mt, _ = _to_tiles(m)
    rows = gt.shape[0]
    hyper = jnp.stack([
        jnp.float32(lr), jnp.float32(beta1), jnp.float32(beta2),
        jnp.float32(weight_decay)
    ]).reshape(1, 4)
    block, spec = _row_spec(rows)
    p_new, m_new, bf16 = pl.pallas_call(
        _lion_kernel,
        grid=(rows // block, ),
        in_specs=[
            pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM),
            spec, spec, spec
        ],
        out_specs=[spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct(gt.shape, jnp.float32),
            jax.ShapeDtypeStruct(gt.shape, jnp.float32),
            jax.ShapeDtypeStruct(gt.shape, jnp.dtype(out_dtype)),
        ],
        input_output_aliases={2: 0, 3: 1},
        interpret=_interpret(),
    )(hyper, gt, pt, mt)
    shape = grad.shape
    return (_from_tiles(bf16, n, shape, out_dtype),
            _from_tiles(p_new, n, shape, jnp.float32),
            _from_tiles(m_new, n, shape, jnp.float32))


# ---------------------------------------------------------------- lamb
def _lamb_phase1_kernel(h_ref, g_ref, p_ref, m_ref, v_ref, u_ref, mo_ref,
                        vo_ref, pn_ref, un_ref):
    b1, b2, eps, wd, c1, c2 = (h_ref[0, i] for i in range(6))
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        pn_ref[0, 0] = 0.0
        un_ref[0, 0] = 0.0

    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:]
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    u = (m / c1) / (jnp.sqrt(v / c2) + eps) + wd * p
    u_ref[:] = u
    mo_ref[:] = m
    vo_ref[:] = v
    pn_ref[0, 0] += jnp.sum(p * p)
    un_ref[0, 0] += jnp.sum(u * u)


def _lamb_phase2_kernel(h_ref, p_ref, u_ref, po_ref, bf_ref):
    scaled_lr = h_ref[0, 0]
    p_new = p_ref[:] - scaled_lr * u_ref[:]
    po_ref[:] = p_new
    bf_ref[:] = p_new.astype(bf_ref.dtype)


def fused_lamb_step(grad, master, m, v, *, lr, beta1, beta2, eps,
                    weight_decay, count, bias_correction=True,
                    max_coeff=10.0, min_coeff=0.01, out_dtype=jnp.bfloat16):
    """One fused LAMB update with per-tensor trust ratio (reference
    ``csrc/lamb/fused_lamb_cuda_kernel.cu``; two-phase like the CUDA kernel's
    reduction + apply structure).  Returns
    ``(param_out_dtype, master_f32, m_f32, v_f32)``."""
    gt, n = _to_tiles(grad)
    pt, _ = _to_tiles(master.astype(jnp.float32))
    mt, _ = _to_tiles(m)
    vt, _ = _to_tiles(v)
    rows = gt.shape[0]
    cf = jnp.float32(count)
    c1 = 1.0 - jnp.float32(beta1)**cf if bias_correction else jnp.float32(1)
    c2 = 1.0 - jnp.float32(beta2)**cf if bias_correction else jnp.float32(1)
    hyper = jnp.stack([
        jnp.float32(beta1), jnp.float32(beta2), jnp.float32(eps),
        jnp.float32(weight_decay), c1, c2
    ]).reshape(1, 6)
    block, spec = _row_spec(rows)
    norm_spec = pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM)
    u, m_new, v_new, p_sq, u_sq = pl.pallas_call(
        _lamb_phase1_kernel,
        grid=(rows // block, ),
        in_specs=[
            pl.BlockSpec((1, 6), lambda i: (0, 0), memory_space=pltpu.SMEM),
            spec, spec, spec, spec
        ],
        out_specs=[spec, spec, spec, norm_spec, norm_spec],
        out_shape=[
            jax.ShapeDtypeStruct(gt.shape, jnp.float32),
            jax.ShapeDtypeStruct(gt.shape, jnp.float32),
            jax.ShapeDtypeStruct(gt.shape, jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        input_output_aliases={3: 1, 4: 2},
        interpret=_interpret(),
    )(hyper, gt, pt, mt, vt)

    p_norm = jnp.sqrt(p_sq[0, 0])
    u_norm = jnp.sqrt(u_sq[0, 0])
    ratio = jnp.where(
        (p_norm > 0.0) & (u_norm > 0.0),
        jnp.clip(p_norm / u_norm, min_coeff, max_coeff), 1.0)
    scaled = (jnp.float32(lr) * ratio).reshape(1, 1)

    p_new, bf16 = pl.pallas_call(
        _lamb_phase2_kernel,
        grid=(rows // block, ),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            spec, spec
        ],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct(gt.shape, jnp.float32),
            jax.ShapeDtypeStruct(gt.shape, jnp.dtype(out_dtype)),
        ],
        input_output_aliases={1: 0},
        interpret=_interpret(),
    )(scaled, pt, u)
    shape = grad.shape
    return (_from_tiles(bf16, n, shape, out_dtype),
            _from_tiles(p_new, n, shape, jnp.float32),
            _from_tiles(m_new, n, shape, jnp.float32),
            _from_tiles(v_new, n, shape, jnp.float32))
