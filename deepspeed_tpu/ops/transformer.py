"""DeepSpeedTransformerLayer — the training transformer block, TPU-native.

Reference: ``ops/transformer/transformer.py`` (``DeepSpeedTransformerLayer``
:296, ``DeepSpeedTransformerConfig`` :34) binding to ~9k LoC of fused CUDA
encoder kernels (``csrc/transformer/``: gemm+bias+gelu+dropout+LN+softmax
fusion and workspace reuse).  On TPU the whole layer is one XLA program —
the fusions the CUDA suite hand-writes are emitted by the compiler (measured
in ``docs/kernel_fusion.md``), and attention routes through the Pallas flash
kernel.  What remains worth keeping from the reference API is the module
itself: a BERT-style encoder layer with the same config surface
(pre/post-LN, dropout ratios, gelu checkpointing) so reference training
scripts port directly.
"""

from dataclasses import dataclass, field, fields
import json

import jax
import jax.numpy as jnp
import flax.linen as nn


@dataclass(frozen=True)
class DeepSpeedTransformerConfig:
    """Reference ``DeepSpeedTransformerConfig`` (``transformer.py:34``) —
    same knobs; CUDA-only ones (``normalize_invertible``, ``stochastic_mode``,
    ``attn_dropout_checkpoint``) are accepted and ignored (XLA manages
    workspaces and recompute)."""
    batch_size: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1     # -1 → 4*hidden
    heads: int = -1
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    bf16: bool = True
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False
    training: bool = True

    @property
    def ffn_size(self):
        return (self.intermediate_size if self.intermediate_size > 0
                else 4 * self.hidden_size)

    @property
    def dtype(self):
        if self.fp16:
            return jnp.float16
        return jnp.bfloat16 if self.bf16 else jnp.float32

    @classmethod
    def from_dict(cls, json_object):
        known = {f.name for f in fields(cls)}
        return cls(**{k: v for k, v in json_object.items() if k in known})

    @classmethod
    def from_json_file(cls, json_file):
        with open(json_file) as f:
            return cls.from_dict(json.load(f))


def _dense(cfg, n, name):
    return nn.Dense(n, dtype=cfg.dtype, param_dtype=jnp.float32,
                    kernel_init=nn.initializers.normal(
                        cfg.initializer_range), name=name)


class _FFN(nn.Module):
    """gelu MLP sub-block — a Module (not a closure) so gelu_checkpoint can
    wrap it with nn.remat (jax.checkpoint over flax submodule creation
    leaks tracers)."""
    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, h, deterministic):
        cfg = self.config
        inner = nn.gelu(_dense(cfg, cfg.ffn_size, "inter")(h))
        out = _dense(cfg, cfg.hidden_size, "output")(inner)
        if cfg.hidden_dropout_ratio > 0 and not deterministic:
            out = nn.Dropout(cfg.hidden_dropout_ratio)(
                out, deterministic=False)
        return out


class DeepSpeedTransformerLayer(nn.Module):
    """BERT-style encoder layer (reference ``transformer.py:296``).

    ``__call__(hidden_states, attention_mask=None, deterministic=None)`` →
    hidden states ``[B, S, D]`` (tuple if ``config.return_tuple``).
    ``attention_mask``: additive mask broadcastable to ``[B, 1, S, S]`` or a
    boolean/0-1 key mask ``[B, S]``.  ``deterministic`` defaults to
    ``not config.training`` so ported reference scripts get dropout during
    training without extra plumbing.
    """
    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None,
                 deterministic=None, pld_theta=None):
        cfg = self.config
        if deterministic is None:
            deterministic = not cfg.training
        D, H = cfg.hidden_size, cfg.heads
        Dh = D // H
        dtype = cfg.dtype
        dense = lambda n, name: _dense(cfg, n, name)
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                       dtype=dtype, param_dtype=jnp.float32,
                                       name=name)
        x = hidden_states.astype(dtype)
        B, S, _ = x.shape
        attn_drop = cfg.attn_dropout_ratio > 0 and not deterministic

        def attn_block(h):
            qkv = dense(3 * D, "attn_qkv")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B, S, H, Dh)
            k = k.reshape(B, S, H, Dh)
            v = v.reshape(B, S, H, Dh)
            if attention_mask is None and not attn_drop:
                # flash/XLA core (no dropout support in the kernel)
                from .attention import attention_core
                out = attention_core(q, k, v, causal=False)
            else:
                logits = jnp.einsum("bshd,bthd->bhst", q, k) / Dh**0.5
                logits = logits.astype(jnp.float32)
                if attention_mask is not None:
                    m = attention_mask
                    if m.ndim == 2:      # [B, S] key mask → additive
                        m = jnp.where(m.astype(bool), 0.0,
                                      jnp.finfo(jnp.float32).min)
                        m = m[:, None, None, :]
                    logits = logits + m.astype(jnp.float32)
                p = jax.nn.softmax(logits, axis=-1).astype(dtype)
                if attn_drop:
                    p = nn.Dropout(cfg.attn_dropout_ratio)(
                        p, deterministic=False)
                out = jnp.einsum("bhst,bthd->bshd", p, v)
            out = dense(D, "attn_out")(out.reshape(B, S, D))
            if cfg.hidden_dropout_ratio > 0 and not deterministic:
                out = nn.Dropout(cfg.hidden_dropout_ratio)(
                    out, deterministic=False)
            return out

        ffn_cls = (nn.remat(_FFN, static_argnums=(2, ))
                   if cfg.gelu_checkpoint else _FFN)
        ffn = ffn_cls(cfg)
        # share the parent scope so the FFN's params stay at the layer's
        # top level ("inter"/"output"), not nested under a submodule name
        nn.share_scope(self, ffn)

        def layer_body(x):
            if cfg.pre_layer_norm:
                x = x + attn_block(ln("attn_ln")(x))
                x = x + ffn(ln("ffn_ln")(x), deterministic)
            else:
                x = ln("attn_ln")(x + attn_block(x))
                x = ln("ffn_ln")(x + ffn(x, deterministic))
            return x

        if pld_theta is not None and not deterministic:
            # progressive layer drop (engine pld_theta, reference PLD):
            # keep this layer with probability theta, else identity.  The
            # scalar-predicate lax.cond actually SKIPS the layer's FLOPs at
            # runtime (a jnp.where would compute both branches).
            keep = jax.random.bernoulli(
                self.make_rng("pld"), jnp.asarray(pld_theta, jnp.float32))
            # flax: initialize params unconditionally, run conditionally
            # (nn.cond lifts module state through the branch)
            if self.is_initializing():
                x = layer_body(x)
            else:
                x = nn.cond(keep, lambda mdl, t: layer_body(t),
                            lambda mdl, t: t, self, x)
        else:
            x = layer_body(x)
        return (x, ) if cfg.return_tuple else x
