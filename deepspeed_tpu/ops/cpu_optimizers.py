"""Host (CPU) optimizers for ZeRO-Offload — bindings for the native SIMD
kernels (``csrc/optimizers/cpu_optimizers.cpp``).

Reference: ``deepspeed/ops/adam/cpu_adam.py`` (``DeepSpeedCPUAdam``) backed
by ``csrc/adam/cpu_adam_impl.cpp``; same for adagrad/lion.  These operate
in-place on numpy fp32 master state living in host RAM, optionally emitting
a bf16 shadow for the device copy-back.
"""

import ctypes

import numpy as np

from .op_builder import NativeOpBuilder, register_op_builder


@register_op_builder
class CPUAdamBuilder(NativeOpBuilder):
    NAME = "cpu_adam"
    SOURCES = ("csrc/optimizers/cpu_optimizers.cpp", )
    EXTRA_CFLAGS = ("-fopenmp", "-march=native", "-funroll-loops")
    EXTRA_LDFLAGS = ("-fopenmp", )

    def _load_impl(self):
        lib = super()._load_impl()
        lib.ds_cpu_adam_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p
        ]
        lib.ds_cpu_adagrad_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_void_p
        ]
        lib.ds_cpu_lion_step.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_void_p
        ]
        lib.ds_cpu_sq_norm.restype = ctypes.c_double
        lib.ds_cpu_sq_norm.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        return lib


# alias builders so the reference names resolve in ds_report
@register_op_builder
class CPUAdagradBuilder(CPUAdamBuilder):
    NAME = "cpu_adagrad"


@register_op_builder
class CPULionBuilder(CPUAdamBuilder):
    NAME = "cpu_lion"


def _ptr(arr):
    return arr.ctypes.data_as(ctypes.c_void_p)


def _check(name, arr, n, dtype=np.float32):
    if arr.dtype != dtype or not arr.flags["C_CONTIGUOUS"]:
        raise ValueError(f"{name} must be C-contiguous {dtype}")
    if arr.size != n:
        raise ValueError(f"{name} size {arr.size} != {n}")


class DeepSpeedCPUAdam:
    """In-place host Adam/AdamW (reference ``ops/adam/cpu_adam.py:18``)."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adamw_mode=True):
        self._lib = CPUAdamBuilder().load()
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0

    def step(self, param, grad, exp_avg, exp_avg_sq, bf16_out=None, lr=None):
        n = param.size
        _check("param", param, n)
        _check("grad", grad, n)
        _check("exp_avg", exp_avg, n)
        _check("exp_avg_sq", exp_avg_sq, n)
        if bf16_out is not None:
            _check("bf16_out", bf16_out, n, np.uint16)
        self.step_count += 1
        self._lib.ds_cpu_adam_step(
            _ptr(param), _ptr(grad), _ptr(exp_avg), _ptr(exp_avg_sq), n,
            float(lr if lr is not None else self.lr), float(self.betas[0]),
            float(self.betas[1]), float(self.eps), float(self.weight_decay),
            self.step_count, int(self.adamw_mode),
            _ptr(bf16_out) if bf16_out is not None else None)


class DeepSpeedCPUAdagrad:
    def __init__(self, lr=1e-2, eps=1e-10, weight_decay=0.0):
        self._lib = CPUAdamBuilder().load()
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay

    def step(self, param, grad, state_sum, bf16_out=None, lr=None):
        n = param.size
        _check("param", param, n)
        _check("grad", grad, n)
        _check("state_sum", state_sum, n)
        if bf16_out is not None:
            _check("bf16_out", bf16_out, n, np.uint16)
        self._lib.ds_cpu_adagrad_step(
            _ptr(param), _ptr(grad), _ptr(state_sum), n,
            float(lr if lr is not None else self.lr), float(self.eps),
            float(self.weight_decay),
            _ptr(bf16_out) if bf16_out is not None else None)


class DeepSpeedCPULion:
    def __init__(self, lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0):
        self._lib = CPUAdamBuilder().load()
        self.lr = lr
        self.betas = betas
        self.weight_decay = weight_decay

    def step(self, param, grad, exp_avg, bf16_out=None, lr=None):
        n = param.size
        _check("param", param, n)
        _check("grad", grad, n)
        _check("exp_avg", exp_avg, n)
        if bf16_out is not None:
            _check("bf16_out", bf16_out, n, np.uint16)
        self._lib.ds_cpu_lion_step(
            _ptr(param), _ptr(grad), _ptr(exp_avg), n,
            float(lr if lr is not None else self.lr), float(self.betas[0]),
            float(self.betas[1]), float(self.weight_decay),
            _ptr(bf16_out) if bf16_out is not None else None)


def cpu_sq_norm(grad):
    lib = CPUAdamBuilder().load()
    _check("grad", grad, grad.size)
    return float(lib.ds_cpu_sq_norm(_ptr(grad), grad.size))
