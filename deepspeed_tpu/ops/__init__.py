from .op_builder import (ALL_OPS, NativeOpBuilder, OpBuilder, PallasOpBuilder,
                         get_op_builder_class, register_op_builder)

# importing the op modules populates ALL_OPS (ds_report's compat matrix —
# reference op_builder/all_ops.py eagerly enumerates the same way).
# cpu_optimizers LAST: registration is last-wins, and the native C++
# builders must own the cpu_* names (lion.py also registers a cpu_lion)
from . import adam, aio, lamb, lion  # noqa: F401, E402
from . import cpu_optimizers  # noqa: F401, E402


@register_op_builder
class FlashAttentionBuilder(PallasOpBuilder):
    NAME = "flash_attn"
    MODULE = "deepspeed_tpu.ops.pallas.flash_attention"


@register_op_builder
class PagedAttentionBuilder(PallasOpBuilder):
    NAME = "ragged_ops"  # reference inference-v2 kernel suite name
    MODULE = "deepspeed_tpu.ops.pallas.paged_attention"


@register_op_builder
class QuantizerBuilder(PallasOpBuilder):
    NAME = "quantizer"
    MODULE = "deepspeed_tpu.ops.pallas.quantizer"


@register_op_builder
class FPQuantizerBuilder(PallasOpBuilder):
    NAME = "fp_quantizer"
    MODULE = "deepspeed_tpu.ops.fp_quantizer"


@register_op_builder
class SparseAttnBuilder(PallasOpBuilder):
    NAME = "sparse_attn"
    MODULE = "deepspeed_tpu.ops.sparse_attention"


@register_op_builder
class EvoformerAttnBuilder(PallasOpBuilder):
    NAME = "evoformer_attn"
    MODULE = "deepspeed_tpu.ops.deepspeed4science.evoformer_attn"


@register_op_builder
class TransformerBuilder(PallasOpBuilder):
    NAME = "transformer"  # reference training transformer kernel suite
    MODULE = "deepspeed_tpu.ops.transformer"
