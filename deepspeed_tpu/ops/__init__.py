from .op_builder import (ALL_OPS, NativeOpBuilder, OpBuilder, PallasOpBuilder,
                         get_op_builder_class, register_op_builder)
