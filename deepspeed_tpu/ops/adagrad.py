"""Adagrad — TPU rebuild of the reference ``deepspeed/ops/adagrad/cpu_adagrad
.py`` (``DeepSpeedCPUAdagrad``, native kernel ``csrc/adagrad/cpu_adagrad.cpp``).

Same math as the native host kernel in ``csrc/optimizers/cpu_optimizers.cpp``
(``ds_cpu_adagrad_step``): ``g += wd·p; s += g²; p -= lr·g/(√s + eps)`` —
so the host-offload step (`engine._try_host_offload_step`) and this fused
device transformation produce bit-comparable trajectories.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .adam import (GradientTransformation, no_lr_override, resolve_lr)


class ScaleByAdagradState(NamedTuple):
    count: jnp.ndarray  # int32 scalar
    sum: any            # per-param squared-grad accumulator
    lr_override: any = None


def fused_adagrad(lr=1e-2, eps=1e-10, weight_decay=0.0, lr_fn=None):
    """Fused Adagrad update (reference ``DeepSpeedCPUAdagrad`` semantics)."""

    def init(params):
        return ScaleByAdagradState(
            count=jnp.zeros((), jnp.int32),
            sum=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, jnp.float32), params),
            lr_override=no_lr_override())

    def update(grads, state, params):
        count = state.count + 1
        cur_lr = resolve_lr(lr_fn(count) if lr_fn is not None else lr, state)

        def upd(g, p, s):
            g = g.astype(jnp.float32)
            if weight_decay:
                g = g + weight_decay * p.astype(jnp.float32)
            s_new = s + g * g
            return -cur_lr * g / (jnp.sqrt(s_new) + eps), s_new

        flat = jax.tree_util.tree_map(upd, grads, params, state.sum)
        updates = jax.tree_util.tree_map(lambda t: t[0], flat,
                                         is_leaf=lambda t: isinstance(t, tuple))
        new_sum = jax.tree_util.tree_map(lambda t: t[1], flat,
                                         is_leaf=lambda t: isinstance(t, tuple))
        return updates, ScaleByAdagradState(count=count, sum=new_sum,
                                            lr_override=state.lr_override)

    return GradientTransformation(init=init, update=update)


# Reference import-surface alias (``deepspeed/ops/adagrad``).  The
# "cpu_adagrad" op builder is registered by ops/cpu_optimizers.py (the
# native kernel this transformation mirrors).
DeepSpeedCPUAdagrad = fused_adagrad
