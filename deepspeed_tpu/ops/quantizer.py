"""Back-compat import path (reference ``deepspeed/ops/quantizer``) — the
blockwise int8/int4 quantizer implementation lives in
``ops/pallas/quantizer`` (Pallas kernel + XLA fallback)."""

from .pallas.quantizer import (dequantize_blockwise,  # noqa: F401
                               quantize_blockwise)
