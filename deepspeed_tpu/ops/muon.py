"""Muon — momentum + Newton-Schulz orthogonalized updates for hidden 2-D
params, AdamW for everything else.

Config name ``"muon"`` (``runtime/config.py MUON_OPTIMIZER``; later reference
DeepSpeed versions ship a Muon optimizer — the pinned v0.16.2 names it only).
TPU fit: the whole update is five matmuls per 2-D param (the Newton-Schulz
iteration), which lands on the MXU; no data-dependent control flow.

Semantics follow the public Muon recipe (Keller Jordan et al.):
* hidden-layer 2-D matrices: SGD-momentum accumulate (nesterov optional),
  then replace the momentum buffer with its approximate orthogonalization
  NS5(m) scaled by sqrt(max(1, rows/cols));
* embeddings, LM head, and non-2-D params (biases, norms): AdamW with its
  own lr — the recipe explicitly EXCLUDES embed/head params from
  orthogonalization.  Exclusion is by parameter path (``embed``/``wte``/
  ``wpe``/``head``/``vocab`` substrings) plus an ndim != 2 catch-all;
  override with the ``exclude`` predicate.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .adam import GradientTransformation, no_lr_override, resolve_lr

# Quintic Newton-Schulz coefficients from the public Muon implementation —
# tuned for fast convergence of the polar factor at bf16-tolerant precision.
_NS_COEFFS = (3.4445, -4.7750, 2.0315)

_EXCLUDE_SUBSTRINGS = ("embed", "wte", "wpe", "head", "vocab")


class MuonState(NamedTuple):
    count: jnp.ndarray
    mu: any   # momentum (muon leaves) / exp_avg (adamw leaves)
    nu: any   # exp_avg_sq for adamw leaves; scalar placeholder for muon ones
    lr_override: any = None


def default_muon_exclude(path, leaf):
    """True → AdamW; the public recipe excludes embeddings/head and every
    non-2-D parameter from orthogonalization."""
    if leaf.ndim != 2:
        return True
    lowered = path.lower()
    return any(s in lowered for s in _EXCLUDE_SUBSTRINGS)


def newton_schulz_orthogonalize(g, steps=5, eps=1e-7):
    """Approximate UV^T (polar factor) of a 2-D matrix via the quintic
    Newton-Schulz iteration; runs in float32 on the MXU."""
    a, b, c = _NS_COEFFS
    x = g.astype(jnp.float32)
    transposed = x.shape[0] > x.shape[1]
    if transposed:
        x = x.T
    x = x / (jnp.linalg.norm(x) + eps)

    def body(x, _):
        xxt = x @ x.T
        return a * x + (b * xxt + c * (xxt @ xxt)) @ x, None

    x, _ = jax.lax.scan(body, x, None, length=steps)
    if transposed:
        x = x.T
    return x


def muon(lr=2e-2, momentum=0.95, nesterov=True, ns_steps=5,
         weight_decay=0.0, adamw_lr=3e-4, adamw_betas=(0.9, 0.95),
         adamw_eps=1e-8, exclude=default_muon_exclude, lr_fn=None):
    """Muon GradientTransformation (engine-facing, ZeRO/TP compatible: pure
    per-leaf math plus matmuls — GSPMD shards them like any other op).

    ``lr``/``lr_fn`` drive the muon leaves; ``adamw_lr`` scales
    proportionally when a schedule is active (adamw_lr · lr_t / lr)."""
    b1, b2 = adamw_betas

    def init(params):
        mu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        nu = treedef.unflatten([
            jnp.zeros_like(leaf, dtype=jnp.float32)
            if exclude(jax.tree_util.keystr(kp), leaf)
            else jnp.zeros((), jnp.float32)  # placeholder: muon leaf
            for kp, leaf in flat])
        return MuonState(count=jnp.zeros((), jnp.int32), mu=mu, nu=nu,
                         lr_override=no_lr_override())

    def update(grads, state, params):
        count = state.count + 1
        cur_lr = resolve_lr(lr_fn(count) if lr_fn is not None else lr, state)
        # follow the schedule's shape; lr=0 (freeze-muon-leaves / warmup-
        # from-zero base lr) must not divide by zero — the adamw leaves then
        # run at their own configured rate (ADVICE r3)
        aw_lr = adamw_lr * (cur_lr / lr) if lr else adamw_lr
        bc1 = 1.0 - b1**count.astype(jnp.float32)
        bc2 = 1.0 - b2**count.astype(jnp.float32)

        def upd_muon(g, m, p):
            g = g.astype(jnp.float32)
            m_ = momentum * m + g
            d = (g + momentum * m_) if nesterov else m_
            o = newton_schulz_orthogonalize(d, steps=ns_steps)
            d = o * jnp.sqrt(jnp.maximum(1.0, p.shape[0] / p.shape[1]))
            if weight_decay != 0.0:
                d = d + weight_decay * p.astype(jnp.float32)
            return (-cur_lr * d).astype(p.dtype), m_, jnp.zeros((),
                                                               jnp.float32)

        def upd_adamw(g, m, v, p):
            g = g.astype(jnp.float32)
            m_ = b1 * m + (1 - b1) * g
            v_ = b2 * v + (1 - b2) * (g * g)
            step = (m_ / bc1) / (jnp.sqrt(v_ / bc2) + adamw_eps)
            if weight_decay != 0.0:
                step = step + weight_decay * p.astype(jnp.float32)
            return (-aw_lr * step).astype(p.dtype), m_, v_

        flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
        flat_m = treedef.flatten_up_to(state.mu)
        flat_v = treedef.flatten_up_to(state.nu)
        flat_p = treedef.flatten_up_to(params)
        outs = []
        for (kp, g), m, v, p in zip(flat, flat_m, flat_v, flat_p):
            if exclude(jax.tree_util.keystr(kp), p):
                outs.append(upd_adamw(g, m, v, p))
            else:
                outs.append(upd_muon(g, m, p))
        return (treedef.unflatten([o[0] for o in outs]),
                MuonState(count=count,
                          mu=treedef.unflatten([o[1] for o in outs]),
                          nu=treedef.unflatten([o[2] for o in outs]),
                          lr_override=state.lr_override))

    return GradientTransformation(init=init, update=update)
