"""Floating-point quantization — FP8 / FP6 / FP12 (reference
``csrc/fp_quantizer/fp_quantize.cu`` + ``deepspeed/ops/fp_quantizer/
quantize.py`` API; backs FP6-LLM-style weight-only inference quant and the
qwZ ``fp8``/``fp6`` wire formats).

Format is parametrized exactly like the reference: ``q_bits`` total with
``mantissa_bits`` mantissa → ``exp_bits = q_bits - mantissa_bits - 1``:

    (8, 3) = e4m3   (native jnp.float8_e4m3fn cast on TPU — zero bit math)
    (6, 2) = e3m2   (FP6-LLM format, max 28)
    (12, 7) = e4m7

Per-group symmetric scaling (scale = absmax / fmt_max) like the int8
quantizer; codes are bit-packed for transport (4×6b → 3B, 2×12b → 3B).

TPU design note: the heavy op is the grouped absmax + round-to-grid, done by
one Pallas kernel (or a single XLA fusion on the fallback path); the packing
is pure lane-local integer shifts that XLA fuses into the same program — the
reference needs 850 LoC of CUDA for what the TPU compiler mostly does for
free here.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas._common import interpret_mode as _interpret
from .pallas.quantizer import _group_view, _pick_block

_LANES = 128


def _fmt(q_bits, mantissa_bits):
    exp_bits = q_bits - mantissa_bits - 1
    if exp_bits < 2:
        raise ValueError(f"q_bits={q_bits}, mantissa_bits={mantissa_bits} "
                         "leaves <2 exponent bits")
    bias = 2 ** (exp_bits - 1) - 1
    max_unb = (2 ** exp_bits - 1) - bias
    maxv = (2.0 - 2.0 ** (-mantissa_bits)) * 2.0 ** max_unb
    return exp_bits, bias, max_unb, maxv


def _floor_log2(a):
    """Exact floor(log2(a)) for normal positive fp32, via the exponent bits
    (``frexp`` has no Mosaic lowering; this is shifts on the VPU)."""
    bits = jax.lax.bitcast_convert_type(a.astype(jnp.float32), jnp.uint32)
    return (jnp.right_shift(bits, 23) & 0xFF).astype(jnp.int32) - 127


def round_to_fp_grid(y, q_bits, mantissa_bits):
    """Round ``y`` (already scaled into range) to the nearest representable
    value of the (q_bits, mantissa_bits) float grid.  Subnormals included;
    values beyond the grid max saturate.  Pure elementwise — differentiable
    under a straight-through estimator."""
    exp_bits, bias, max_unb, maxv = _fmt(q_bits, mantissa_bits)
    a = jnp.abs(y.astype(jnp.float32))
    # exponent of each value; clamp to the normal range (min side gives the
    # subnormal step automatically)
    e = _floor_log2(jnp.maximum(a, jnp.finfo(jnp.float32).tiny))
    e = jnp.clip(e, 1 - bias, max_unb)
    step = jnp.exp2((e - mantissa_bits).astype(jnp.float32))
    q = jnp.round(a / step) * step
    q = jnp.minimum(q, maxv)
    return jnp.sign(y) * q


def encode_fp(v, q_bits, mantissa_bits):
    """Exactly-representable value → integer code (sign|exp|mantissa)."""
    exp_bits, bias, max_unb, _ = _fmt(q_bits, mantissa_bits)
    a = jnp.abs(v.astype(jnp.float32))
    sign = (v < 0).astype(jnp.uint32)
    e = _floor_log2(jnp.maximum(a, jnp.finfo(jnp.float32).tiny))
    normal = a >= 2.0 ** (1 - bias)
    efield = jnp.where(normal, e + bias, 0).astype(jnp.uint32)
    # a / 2^e in [1, 2) for normals — exact power-of-two scaling
    man_norm = jnp.round((a * jnp.exp2(-e.astype(jnp.float32)) - 1.0)
                         * 2.0 ** mantissa_bits)
    man_sub = jnp.round(a * 2.0 ** (mantissa_bits - (1 - bias)))
    mfield = jnp.where(normal, man_norm, man_sub).astype(jnp.uint32)
    mfield = jnp.where(a == 0.0, 0, mfield)
    efield = jnp.where(a == 0.0, 0, efield)
    return (sign << (q_bits - 1)) | (efield << mantissa_bits) | mfield


def decode_fp(code, q_bits, mantissa_bits, dtype=jnp.float32):
    """Integer code → value."""
    exp_bits, bias, max_unb, _ = _fmt(q_bits, mantissa_bits)
    code = code.astype(jnp.uint32)
    sign = (code >> (q_bits - 1)) & 0x1
    efield = (code >> mantissa_bits) & ((1 << exp_bits) - 1)
    mfield = code & ((1 << mantissa_bits) - 1)
    normal = efield > 0
    mag = jnp.where(
        normal,
        (1.0 + mfield.astype(jnp.float32) * 2.0 ** (-mantissa_bits))
        * jnp.exp2(efield.astype(jnp.float32) - bias),
        mfield.astype(jnp.float32)
        * 2.0 ** ((1 - bias) - mantissa_bits))
    return (jnp.where(sign == 1, -mag, mag)).astype(dtype)


# ----------------------------------------------------------------- packing
def pack_codes(codes, q_bits):
    """[N] uint32 codes → packed uint8.  6-bit: 4 → 3 bytes; 12-bit: 2 → 3
    bytes; 8-bit: identity bytes."""
    if q_bits == 8:
        return codes.astype(jnp.uint8)
    if q_bits == 6:
        c = codes.reshape(-1, 4)
        b0 = (c[:, 0] << 2) | (c[:, 1] >> 4)
        b1 = ((c[:, 1] & 0xF) << 4) | (c[:, 2] >> 2)
        b2 = ((c[:, 2] & 0x3) << 6) | c[:, 3]
        return jnp.stack([b0, b1, b2], axis=1).astype(jnp.uint8).reshape(-1)
    if q_bits == 12:
        c = codes.reshape(-1, 2)
        b0 = c[:, 0] >> 4
        b1 = ((c[:, 0] & 0xF) << 4) | (c[:, 1] >> 8)
        b2 = c[:, 1] & 0xFF
        return jnp.stack([b0, b1, b2], axis=1).astype(jnp.uint8).reshape(-1)
    raise ValueError(f"no packing for q_bits={q_bits}")


def unpack_codes(packed, q_bits, n):
    if q_bits == 8:
        return packed.astype(jnp.uint32)[:n]
    p = packed.astype(jnp.uint32).reshape(-1, 3)
    if q_bits == 6:
        c0 = p[:, 0] >> 2
        c1 = ((p[:, 0] & 0x3) << 4) | (p[:, 1] >> 4)
        c2 = ((p[:, 1] & 0xF) << 2) | (p[:, 2] >> 6)
        c3 = p[:, 2] & 0x3F
        return jnp.stack([c0, c1, c2, c3], axis=1).reshape(-1)[:n]
    if q_bits == 12:
        c0 = (p[:, 0] << 4) | (p[:, 1] >> 4)
        c1 = ((p[:, 1] & 0xF) << 8) | p[:, 2]
        return jnp.stack([c0, c1], axis=1).reshape(-1)[:n]
    raise ValueError(f"no packing for q_bits={q_bits}")


# ------------------------------------------------------------- pallas core
def _fpq_kernel(x_ref, code_ref, s_ref, *, q_bits, mantissa_bits, maxv):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / maxv)
    v = round_to_fp_grid(x / scale, q_bits, mantissa_bits)
    code_ref[:] = encode_fp(v, q_bits, mantissa_bits).astype(jnp.uint8) \
        if q_bits <= 8 else encode_fp(v, q_bits, mantissa_bits).astype(
            jnp.uint16)
    s_ref[:] = jnp.broadcast_to(scale, s_ref.shape)


def quantize_fp(x, q_bits=8, mantissa_bits=3, group_size=512,
                use_pallas=None):
    """Per-group scaled FP quantization.

    Returns ``(packed_uint8, scales_f32 [groups], meta)``; fp8 e4m3 uses the
    native dtype bytes (bit-identical to a scaled ``astype(float8_e4m3fn)``).
    """
    _, _, _, maxv = _fmt(q_bits, mantissa_bits)
    group_size = max(_LANES, group_size - group_size % _LANES)
    tiles, n, groups = _group_view(x, group_size, _pick_block(group_size))
    meta = (x.shape, x.dtype, groups, q_bits, mantissa_bits, group_size)

    if q_bits == 8 and mantissa_bits == 3:
        # native e4m3fn: max is 448, NOT the generic (2-2^-m)·2^bias = 480 —
        # the "fn" encoding spends the top mantissa code on NaN
        e4m3_max = float(jnp.finfo(jnp.float8_e4m3fn).max)  # 448
        xf = tiles.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
        scale = jnp.where(absmax == 0.0, 1.0, absmax / e4m3_max)
        # clamp: x/scale can round a hair past the format max, and e4m3fn
        # overflows to NaN (no inf encoding)
        q8 = jnp.clip(xf / scale, -e4m3_max,
                      e4m3_max).astype(jnp.float8_e4m3fn)
        return jax.lax.bitcast_convert_type(q8, jnp.uint8), scale[:, 0], meta

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        rows = tiles.shape[0]
        block = min(_pick_block(group_size), rows)
        spec = pl.BlockSpec((block, group_size), lambda i: (i, 0))
        s_spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
        code_dtype = jnp.uint8 if q_bits <= 8 else jnp.uint16
        codes, s = pl.pallas_call(
            functools.partial(_fpq_kernel, q_bits=q_bits,
                              mantissa_bits=mantissa_bits, maxv=maxv),
            grid=(rows // block, ),
            in_specs=[spec],
            out_specs=[spec, s_spec],
            out_shape=[jax.ShapeDtypeStruct(tiles.shape, code_dtype),
                       jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)],
            interpret=_interpret(),
        )(tiles)
        scales = s[:, 0]
    else:
        xf = tiles.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
        scales = jnp.where(absmax == 0.0, 1.0, absmax / maxv)[:, 0]
        v = round_to_fp_grid(xf / scales[:, None], q_bits, mantissa_bits)
        codes = encode_fp(v, q_bits, mantissa_bits)
    return pack_codes(codes.reshape(-1).astype(jnp.uint32), q_bits), \
        scales, meta


def dequantize_fp(packed, scales, meta, use_pallas=None):
    shape, dtype, groups, q_bits, mantissa_bits, group_size = meta
    n = 1
    for d in shape:
        n *= d
    if q_bits == 8 and mantissa_bits == 3:
        q8 = jax.lax.bitcast_convert_type(packed, jnp.float8_e4m3fn)
        vals = q8.astype(jnp.float32) * scales[:, None]
        return vals.reshape(-1)[:n].reshape(shape).astype(dtype)
    total = scales.shape[0] * group_size
    codes = unpack_codes(packed, q_bits, total)
    vals = decode_fp(codes, q_bits, mantissa_bits).reshape(
        scales.shape[0], group_size) * scales[:, None]
    return vals.reshape(-1)[:n].reshape(shape).astype(dtype)


class FP_Quantize:
    """Reference ``deepspeed/ops/fp_quantizer/quantize.py`` API surface.

    Stateless w.r.t. payloads: pass ``meta`` (third return of ``quantize``
    with ``return_meta_tensor=True``) back into ``dequantize`` — one
    instance may serve many tensors/formats concurrently."""

    def __init__(self, group_size=512):
        self.group_size = group_size

    def quantize(self, input, q_bits=8, q_mantisa_bits=3,
                 return_meta_tensor=False):
        packed, scales, meta = quantize_fp(
            input, q_bits=q_bits, mantissa_bits=q_mantisa_bits,
            group_size=self.group_size)
        if return_meta_tensor:
            return packed, scales, meta
        self._last_meta = meta
        return packed, scales

    def dequantize(self, input_q, scale=None, meta=None, q_bits=8,
                   q_mantisa_bits=3):
        if meta is None:
            meta = getattr(self, "_last_meta", None)
            if meta is None:
                raise ValueError(
                    "dequantize needs the meta from quantize(..., "
                    "return_meta_tensor=True) (or an immediately preceding "
                    "quantize call on this instance)")
            if meta[3] != q_bits or meta[4] != q_mantisa_bits:
                raise ValueError(
                    f"payload format ({q_bits},{q_mantisa_bits}) does not "
                    f"match the last quantize call ({meta[3]},{meta[4]}) — "
                    "pass meta explicitly")
        return dequantize_fp(input_q, scale, meta)
