"""Op builder system — TPU-native analog of reference ``op_builder/builder.py``.

The reference JIT-compiles CUDA extensions (``OpBuilder.load()``,
``op_builder/builder.py:514,533``).  Here an "op" is either

* a **Pallas kernel** (compiled by XLA at trace time — ``load()`` just returns
  the python callable), or
* a **native host extension** (C++ via the CPython C API / ctypes, e.g. the
  async-IO library backing NVMe offload), compiled on demand with the system
  toolchain.

``ALL_OPS`` mirrors the reference's registry (``op_builder/all_ops.py``) and
drives ``ds_report``'s compatibility matrix.
"""

import importlib
import os
import shutil
import subprocess

from ..utils.logging import logger


class OpBuilder:
    """Base builder (reference ``op_builder/builder.py:109``)."""

    BUILD_DIR = os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops")

    NAME = "base"

    def __init__(self):
        self._loaded = None

    def name(self):
        return self.NAME

    def absolute_name(self):
        return f"deepspeed_tpu.ops.{self.NAME}"

    def is_compatible(self, verbose=False):
        return True

    def load(self, verbose=True):
        if self._loaded is None:
            self._loaded = self._load_impl()
        return self._loaded

    def _load_impl(self):
        raise NotImplementedError


class PallasOpBuilder(OpBuilder):
    """An op implemented as jax/pallas code: load = import the module."""

    MODULE = None  # dotted path under deepspeed_tpu

    def is_compatible(self, verbose=False):
        try:
            importlib.import_module(self.MODULE)
            return True
        except Exception as e:
            if verbose:
                logger.warning(f"{self.NAME} incompatible: {e}")
            return False

    def _load_impl(self):
        return importlib.import_module(self.MODULE)


class NativeOpBuilder(OpBuilder):
    """A host-side C++ extension compiled with g++ and loaded via ctypes."""

    SOURCES = ()          # repo-relative .cpp paths
    EXTRA_CFLAGS = ()
    EXTRA_LDFLAGS = ()

    def sources(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        return [os.path.join(root, s) for s in self.SOURCES]

    def is_compatible(self, verbose=False):
        return shutil.which("g++") is not None and all(
            os.path.exists(s) for s in self.sources())

    def lib_path(self):
        return os.path.join(self.BUILD_DIR, f"lib{self.NAME}.so")

    def build(self, verbose=True):
        os.makedirs(self.BUILD_DIR, exist_ok=True)
        out = self.lib_path()
        srcs = self.sources()
        newest_src = max(os.path.getmtime(s) for s in srcs)
        if os.path.exists(out) and os.path.getmtime(out) >= newest_src:
            return out
        cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17"] +
               list(self.EXTRA_CFLAGS) + srcs + ["-o", out] +
               list(self.EXTRA_LDFLAGS))
        if verbose:
            logger.info(f"building {self.NAME}: {' '.join(cmd)}")
        subprocess.run(cmd, check=True, capture_output=not verbose)
        return out

    def _load_impl(self):
        import ctypes
        return ctypes.CDLL(self.build())


# Registry: name → builder class.  Populated lazily by the ops modules to
# avoid import cycles; see deepspeed_tpu/ops/__init__.py.
ALL_OPS = {}


def register_op_builder(cls):
    ALL_OPS[cls.NAME] = cls
    return cls


def get_op_builder_class(op_name, accelerator_name="tpu"):
    """Reference ``abstract_accelerator.py:271-286`` get_op_builder hook."""
    _ensure_registered()
    return ALL_OPS.get(op_name)


_registered = False


def _ensure_registered():
    # Import modules whose builders self-register.
    global _registered
    if not _registered:
        _registered = True
        for mod in ("deepspeed_tpu.ops.adam", "deepspeed_tpu.ops.lamb",
                    "deepspeed_tpu.ops.lion", "deepspeed_tpu.ops.quantizer",
                    "deepspeed_tpu.ops.aio",
                    "deepspeed_tpu.ops.cpu_optimizers"):
            try:
                importlib.import_module(mod)
            except ImportError:
                pass
