"""Back-compat import path (reference ``deepspeed/ops/random_ltd``) — the
random layerwise token dropping ops live in
``runtime/data_pipeline/data_routing`` (jnp take/argsort formulation; the
reference's CUDA gather/scatter kernels are XLA ops here)."""

from ..runtime.data_pipeline.data_routing import (  # noqa: F401
    random_ltd_gather, random_ltd_scatter, random_ltd_select)
