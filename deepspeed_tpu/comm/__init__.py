from .comm import (all_gather, all_gather_into_tensor, all_reduce, all_to_all,
                   all_to_all_single, barrier, broadcast, configure,
                   destroy_process_group, ensure_runtime_initialized,
                   get_local_rank, get_rank,
                   get_world_group, get_world_size, init_distributed,
                   initialize_mesh_device, is_initialized, log_summary,
                   new_group, reduce_scatter, reduce_scatter_tensor)
from .backend import MeshBackend, ProcessGroup
from .reduce_op import ReduceOp
from . import functional
