from .comm import (all_gather, all_gather_coalesced, all_gather_into_tensor,
                   all_reduce, all_reduce_coalesced, all_to_all,
                   all_to_all_single, allgather_fn, barrier, broadcast,
                   configure, destroy_process_group,
                   ensure_runtime_initialized, gather,
                   get_all_ranks_from_group, get_global_rank,
                   get_local_rank, get_rank, get_world_group,
                   get_world_size, has_all_gather_into_tensor,
                   has_all_reduce_coalesced, has_coalescing_manager,
                   has_reduce_scatter_tensor, inference_all_reduce,
                   init_distributed, initialize_mesh_device, irecv, is_available,
                   is_initialized, isend, log_summary, monitored_barrier,
                   new_group, recv, recv_obj, reduce, reduce_scatter,
                   reduce_scatter_fn, reduce_scatter_tensor, scatter, send,
                   send_obj, set_collectives_engine, get_collectives_engine)
from .backend import MeshBackend, ProcessGroup
from .reduce_op import ReduceOp
from . import functional
from . import collectives
