"""Communication backend ABC + mesh-backed implementation.

TPU-native re-design of the reference's backend stack
(``deepspeed/comm/backend.py:25`` ABC, ``comm/torch.py:90`` TorchBackend):
instead of wrapping torch.distributed process groups, a *group* here is a set of
mesh axis names over a global ``jax.sharding.Mesh``; every collective is an XLA
collective (`psum`, `all_gather`, `ppermute`, `all_to_all`) emitted via
``shard_map`` over those axes, so the data never leaves HBM and the collective
rides ICI (or DCN for a multi-slice axis).

Two calling conventions are supported:

* **eager / global-array**: collectives take a global (possibly sharded) jax
  array and return a global array — used by engine bring-up code and tests;
* **traced / axis-name** (``deepspeed_tpu.comm.functional``): thin ``jax.lax``
  wrappers used *inside* shard_map/jit regions (Ulysses, MoE, pipeline p2p).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .reduce_op import ReduceOp
from ..utils.logging import logger

_REDUCE_FNS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
}

# jax.jit caches by function identity, so the jitted collective for a given
# (mesh, axes, op, ...) signature must be built once and reused — otherwise
# every call retraces (review finding: hot-path throughput).  functools
# lru_cache keyed on hashable params; Mesh is hashable.
import functools


@functools.lru_cache(maxsize=None)
def _jit_all_reduce(mesh, axes, op, group_size):
    if op == ReduceOp.PRODUCT:
        # no pprod primitive in lax — gather the per-rank contributions and
        # reduce locally (elementwise product across ranks)

        def _k(blk):
            out = blk
            for a in axes:
                out = jnp.prod(
                    jax.lax.all_gather(out, a, axis=0, tiled=False), axis=0)
            return out

        return jax.jit(jax.shard_map(_k, mesh=mesh, check_vma=False,
                                     in_specs=(P(axes), ), out_specs=P()))
    red = _REDUCE_FNS.get(ReduceOp.SUM if op == ReduceOp.AVG else op)
    if red is None:
        raise ValueError(f"unsupported reduce op {op}")

    def _k(blk):
        r = red(blk, axes)
        if op == ReduceOp.AVG:
            r = r / group_size
        return r

    return jax.jit(jax.shard_map(_k, mesh=mesh, check_vma=False,
                                 in_specs=(P(axes), ), out_specs=P()))


@functools.lru_cache(maxsize=None)
def _jit_all_gather(mesh, axes, axis, ndim, tiled):
    in_spec = [None] * ndim
    in_spec[axis] = axes
    in_spec = P(*in_spec)

    def _k(blk):
        out = blk
        for a in reversed(axes):
            out = jax.lax.all_gather(out, a, axis=axis, tiled=tiled)
        return out

    return jax.jit(jax.shard_map(_k, mesh=mesh, check_vma=False,
                                 in_specs=(in_spec, ), out_specs=P()))


@functools.lru_cache(maxsize=None)
def _jit_reduce_scatter(mesh, axes, op, axis, ndim, group_size):
    out_spec = [None] * ndim
    out_spec[axis] = axes
    out_spec = P(*out_spec)

    def _k(blk):
        out = blk
        for a in axes:
            out = jax.lax.psum_scatter(out, a, scatter_dimension=axis, tiled=True)
        if op == ReduceOp.AVG:
            out = out / group_size
        return out

    return jax.jit(jax.shard_map(_k, mesh=mesh, check_vma=False,
                                 in_specs=(P(), ), out_specs=out_spec))


@functools.lru_cache(maxsize=None)
def _jit_broadcast(mesh, axes, src, nblocks):

    def _f(t):
        block = t.shape[0] // nblocks

        def _k(blk):
            full = blk
            for a in reversed(axes):
                full = jax.lax.all_gather(full, a, axis=0, tiled=True)
            return jax.lax.dynamic_slice_in_dim(full, src * block, block, axis=0)

        return jax.shard_map(_k, mesh=mesh, check_vma=False,
                             in_specs=(P(axes), ), out_specs=P())(t)

    return jax.jit(_f)


@functools.lru_cache(maxsize=None)
def _jit_all_to_all(mesh, a, split_axis, concat_axis, ndim):
    in_spec = [None] * ndim
    in_spec[concat_axis] = a
    in_spec = P(*in_spec)
    out_spec = [None] * ndim
    out_spec[split_axis] = a
    out_spec = P(*out_spec)

    def _k(blk):
        return jax.lax.all_to_all(blk, a, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    return jax.jit(jax.shard_map(_k, mesh=mesh, check_vma=False,
                                 in_specs=(in_spec, ), out_specs=out_spec))


class ProcessGroup:
    """A communication group = an ordered tuple of mesh axis names.

    The analog of a torch.distributed process group (reference
    ``comm/torch.py``); ``new_group(ranks)``-style arbitrary rank lists are
    deliberately unsupported — groups are mesh-axis factored, which is the only
    layout that maps onto ICI efficiently (SURVEY.md §2.4 TPU-equivalent note).
    """

    def __init__(self, mesh: Mesh, axis_names):
        if isinstance(axis_names, str):
            axis_names = (axis_names, )
        self.mesh = mesh
        self.axis_names = tuple(axis_names)
        for a in self.axis_names:
            if a not in mesh.axis_names:
                raise ValueError(f"axis {a!r} not in mesh axes {mesh.axis_names}")

    def size(self):
        return int(np.prod([self.mesh.shape[a] for a in self.axis_names], dtype=np.int64)) \
            if self.axis_names else 1

    def effective_axes(self):
        """Axis names with size > 1 (size-1 axes are collective no-ops)."""
        return tuple(a for a in self.axis_names if self.mesh.shape[a] > 1)

    def __repr__(self):
        return f"ProcessGroup(axes={self.axis_names}, size={self.size()})"


class MeshBackend:
    """The single comm backend: a global device mesh + collectives over it."""

    def __init__(self, mesh: Mesh = None, name="ici"):
        self.name = name
        if mesh is None:
            devices = np.array(jax.devices())
            mesh = Mesh(devices, axis_names=("world", ))
        self.mesh = mesh
        self.world_group = ProcessGroup(mesh, mesh.axis_names)
        self.initialized = True

    # ----------------------------------------------------------------- identity
    # Granularity note: under single-controller JAX there is one *process* per
    # host but one *device* per chip.  ``world_size()`` is device-granular (one
    # "rank" per chip, the reference's one-process-per-GPU model) because that
    # is what partitioning math (ZeRO shard counts, batch splits) needs.
    # ``rank()`` is the *process* index and is only valid for host-side
    # concerns (logging, file naming, "is rank 0" checks); per-device ranks
    # exist only inside shard_map via ``functional.axis_index``.  Do NOT write
    # ``total // world_size() * rank()``-style partitioning with these.
    def rank(self):
        return jax.process_index()

    def world_size(self):
        return self.mesh.size

    def process_count(self):
        return jax.process_count()

    # ----------------------------------------------------------------- helpers
    def _group(self, group):
        return group if group is not None else self.world_group

    # -------------------------------------------------------------- collectives
    # Eager/global-array forms.  x is a jax array; if it is replicated the
    # result is the reduction over per-axis *shards* of a leading-dim-sharded
    # view.  The common case in framework code: x already sharded over the
    # group axis on dim 0.
    def all_reduce(self, x, op=ReduceOp.SUM, group=None):
        group = self._group(group)
        fn = _jit_all_reduce(group.mesh, group.axis_names, op, group.size())
        return fn(x)

    def all_gather(self, x, group=None, axis=0, tiled=True):
        """Gather shards along ``axis``; input sharded over group axes."""
        group = self._group(group)
        fn = _jit_all_gather(group.mesh, group.axis_names, axis, x.ndim, tiled)
        return fn(x)

    def reduce_scatter(self, x, op=ReduceOp.SUM, group=None, axis=0):
        """Reduce over the group and scatter along ``axis``.

        Input replicated; output sharded along ``axis`` over group axes.
        The ZeRO-2 gradient path (reference ``stage_1_and_2.py:1045``
        ``average_tensor``) lowers to this.
        """
        group = self._group(group)
        fn = _jit_reduce_scatter(group.mesh, group.axis_names, op, axis, x.ndim,
                                 group.size())
        return fn(x)

    def broadcast(self, x, src=0, group=None):
        """Broadcast ``src`` rank's shard to all ranks of the group.

        With single-controller JAX a replicated global array is already
        "broadcast"; this exists for API parity and for per-rank-distinct
        arrays (input sharded on dim 0).
        """
        group = self._group(group)
        fn = _jit_broadcast(group.mesh, group.axis_names, src, group.size())
        return fn(x)

    def all_to_all(self, x, group=None, split_axis=0, concat_axis=0):
        """All-to-all: split ``split_axis`` across the group, concat received
        chunks along ``concat_axis``.  Ulysses' reshard primitive (reference
        ``sequence/layer.py:182 single_all_to_all``)."""
        group = self._group(group)
        eff = group.effective_axes()
        if len(eff) == 0:
            return x
        if len(eff) != 1:
            raise ValueError(
                f"all_to_all requires a single (effective) mesh axis, got {eff}")
        a = eff[0]
        fn = _jit_all_to_all(group.mesh, a, split_axis, concat_axis, x.ndim)
        return fn(x)

    def barrier(self, group=None):
        group = self._group(group)
        # A psum across the group is a true cross-device barrier once waited on.
        self.all_reduce(jnp.zeros((group.size(), )), op=ReduceOp.SUM,
                        group=group).block_until_ready()

    def log_summary(self):
        logger.info(f"MeshBackend mesh={dict(self.mesh.shape)}")
