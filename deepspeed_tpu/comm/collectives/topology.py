"""Mesh topology factorization — the hierarchy layer of the collectives engine.

A collective group in this codebase is a tuple of mesh axis names
(:class:`deepspeed_tpu.comm.backend.ProcessGroup`).  When such a group spans
both fast intra-node links (ICI) and a slow inter-node fabric (DCN), a flat
single-hop collective pays the DCN price on the FULL payload.  The classic
fix (ZeRO++ hpZ/qgZ, EQuARX, NCCL trees) is hierarchical execution:

    intra-node reduce-scatter  →  inter-node op on 1/N of the data
                               →  intra-node all-gather

This module answers the one question that scheme needs: *how does a group's
axis factorize into (inter-node, intra-node) sub-axes?*  Two shapes exist:

* **multi-axis groups** (``("dp", "ep")``, hpZ's ``("zp_outer", "zp")``):
  mesh axis order is major→minor, and the mesh builders
  (``utils/groups.py:_physical_device_grid``) put the DCN/slice factor on the
  outermost axis — so the group's own axes already ARE the hierarchy:
  first effective axis = inter, the rest = intra.
* **single-axis groups** (``("dp", )`` over a multi-host pod): the axis is
  split into ``(axis + "_out", axis + "_in")`` on a *reshaped* mesh (same
  device order, so ``_in`` spans the physically-adjacent chips — exactly the
  hpZ-mesh construction in ``utils/groups.py:initialize_mesh``).  The split
  point comes from the device metadata (slice / process boundaries) or an
  explicit override (``intra_node_size`` config / ``DS_TPU_INTRA_NODE_SIZE``
  env) — the override is also what makes hierarchy testable on the virtual
  CPU mesh, which has no physical topology.
"""

import functools
import os
from dataclasses import dataclass

import numpy as np

from jax.sharding import Mesh


@dataclass(frozen=True)
class Hierarchy:
    """A group factored into (inter-node, intra-node) mesh axes.

    ``mesh`` is the mesh the hierarchical collective must shard_map over —
    the group's own mesh for multi-axis groups, a reshaped one for a split
    single axis.  ``outer_axes`` ride DCN, ``inner_axes`` ride ICI.
    """
    mesh: Mesh
    outer_axes: tuple
    inner_axes: tuple
    outer_size: int
    inner_size: int

    @property
    def size(self):
        return self.outer_size * self.inner_size

    @property
    def group_axes(self):
        """Axis tuple tiling the group's dim, major→minor (= device order of
        the original flat group axis)."""
        return self.outer_axes + self.inner_axes


def _node_key(device):
    """Physical-locality key: devices sharing it are 'one node' (cheap
    links).  Multi-slice TPU pods expose ``slice_index`` (DCN crosses
    slices); otherwise the host process is the node."""
    s = getattr(device, "slice_index", None)
    if s is not None:
        return ("slice", s)
    return ("process", getattr(device, "process_index", 0))


def axis_intra_size(mesh, axis):
    """How many consecutive devices along ``axis`` share a node, measured at
    the origin of all other axes.  Returns 0 when the axis never leaves the
    node (no hierarchy to exploit) or the run length does not divide the
    axis size (irregular placement — refuse to guess)."""
    devs = np.asarray(mesh.devices)
    i = mesh.axis_names.index(axis)
    idx = [0] * devs.ndim
    idx[i] = slice(None)
    line = list(devs[tuple(idx)].flat)
    n = len(line)
    first = _node_key(line[0])
    run = 1
    while run < n and _node_key(line[run]) == first:
        run += 1
    if run >= n or n % run != 0:
        return 0
    return run


@functools.lru_cache(maxsize=None)
def split_mesh(mesh, axis, inner):
    """Reshape ``axis`` (size n) into ``(axis_out, axis_in)`` = (n/inner,
    inner), device order preserved: ``_in`` is the fastest-varying (physically
    nearest) factor.  Cached — shard_map'd jits key on Mesh identity."""
    names = mesh.axis_names
    devs = np.asarray(mesh.devices)
    i = names.index(axis)
    n = devs.shape[i]
    if inner <= 1 or n % inner != 0:
        raise ValueError(f"cannot split axis {axis!r} of size {n} with "
                         f"inner factor {inner}")
    shape = devs.shape[:i] + (n // inner, inner) + devs.shape[i + 1:]
    new_names = names[:i] + (axis + "_out", axis + "_in") + names[i + 1:]
    return Mesh(devs.reshape(shape), new_names)


def detect_intra_node_size(mesh, axis, override=0):
    """Resolve the intra-node run length for ``axis``: explicit override >
    ``DS_TPU_INTRA_NODE_SIZE`` env > device-metadata probe.  0 = no usable
    hierarchy."""
    if override and override > 1:
        return override
    env = os.environ.get("DS_TPU_INTRA_NODE_SIZE")
    if env:
        try:
            val = int(env)
        except ValueError:
            raise ValueError(
                f"DS_TPU_INTRA_NODE_SIZE={env!r} is not an integer — set "
                "it to the devices-per-node count (e.g. 4), or unset it "
                "for auto-detection") from None
        if val < 0:
            raise ValueError(
                f"DS_TPU_INTRA_NODE_SIZE={env!r} must be non-negative "
                "(0 = auto-detect)")
        return val
    return axis_intra_size(mesh, axis)


def factor_group(group, intra_node_size=0):
    """Factor a ProcessGroup into a :class:`Hierarchy`, or None when there is
    nothing to factor (single node, size-1 group, indivisible split).
    Memoized per (mesh, axes, override) — this sits on the dispatch path of
    every engine collective, and the detection walks device metadata."""
    return _factor_cached(group.mesh, group.effective_axes(),
                          intra_node_size,
                          os.environ.get("DS_TPU_INTRA_NODE_SIZE"))


@functools.lru_cache(maxsize=None)
def _factor_cached(mesh, eff, intra_node_size, _env):
    # _env participates in the key only so an env-var change between calls
    # is not masked by the memo
    if not eff:
        return None
    if len(eff) >= 2:
        outer, inner = eff[:1], eff[1:]
        osz = mesh.shape[outer[0]]
        isz = 1
        for a in inner:
            isz *= mesh.shape[a]
        return Hierarchy(mesh=mesh, outer_axes=outer, inner_axes=inner,
                         outer_size=osz, inner_size=isz)
    axis = eff[0]
    n = mesh.shape[axis]
    s = detect_intra_node_size(mesh, axis, override=intra_node_size)
    if s <= 1 or s >= n or n % s != 0:
        return None
    smesh = split_mesh(mesh, axis, s)
    return Hierarchy(mesh=smesh, outer_axes=(axis + "_out", ),
                     inner_axes=(axis + "_in", ), outer_size=n // s,
                     inner_size=s)


def clear_topology_caches():
    """Drop memoized hierarchies/reshaped meshes so stale Mesh objects can
    be collected (rides ``dist.destroy_process_group`` via
    ``engine.clear_jit_caches``)."""
    _factor_cached.cache_clear()
    split_mesh.cache_clear()
