"""The pluggable collectives engine behind the ``dist.*`` facade.

``comm/comm.py`` owns ONE dispatch point (``_dispatch``); when an engine is
installed and enabled, every eager ``all_reduce`` / ``all_gather`` /
``reduce_scatter`` (and the ``allgather_fn`` / ``reduce_scatter_fn`` /
``*_coalesced`` helpers riding them) is offered to :meth:`CollectivesEngine.
dispatch` first.  The engine picks a *variant*:

    ==================  =============================================
    variant             meaning
    ==================  =============================================
    (None — fallback)   today's flat single-hop collective, bit-exact
    ``hier``            hierarchical all-reduce (fp payload)
    ``q_<fmt>``         quantized payload (all-gather / reduce-scatter)
    ``hier_q_<fmt>``    2-hop: fp intra-node, quantized inter-node
    ==================  =============================================

and returns ``(result, variant, wire_bytes)`` — or None, which means "flat
path, unchanged".  ``wire_bytes`` is the payload actually crossing the
*bottleneck* (inter-node) link, which is what ``utils/comms_logging`` and
``ds_bench`` report; for flat ops it equals the logical message size.

Selection is conservative by construction: a reduce op outside SUM/AVG
(MIN/MAX/PRODUCT), a non-float dtype, an indivisible shape, a message under
``min_message_size``, or a topology with no hierarchy all fall through to
the flat path — optimized never means "sometimes wrong".
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ... import telemetry as _telemetry
from ..reduce_op import ReduceOp
from . import quantized as Q
from .config import CommOptimizations
from .topology import factor_group

_LINEAR_OPS = (ReduceOp.SUM, ReduceOp.AVG)


# ------------------------------------------------------------ jitted kernels
# Cached by (mesh, axes, ...) like comm/backend.py — jax.jit keys on function
# identity, so each signature must map to one function object.

@functools.lru_cache(maxsize=None)
def _jit_hier_all_reduce(mesh, inner_axes, outer_axes, op, total):
    """intra reduce-scatter → inter all-reduce on 1/n_inner → intra
    all-gather.  Input convention matches the flat backend: dim 0 sharded
    over the group (outer-major), output replicated."""

    def _k(blk):
        r = blk
        for a in inner_axes:
            r = jax.lax.psum_scatter(r, a, scatter_dimension=0, tiled=True)
        r = jax.lax.psum(r, outer_axes)
        for a in reversed(inner_axes):
            r = jax.lax.all_gather(r, a, axis=0, tiled=True)
        if op == ReduceOp.AVG:
            r = r / total
        return r

    return jax.jit(jax.shard_map(_k, mesh=mesh, check_vma=False,
                                 in_specs=(P(outer_axes + inner_axes), ),
                                 out_specs=P()))


@functools.lru_cache(maxsize=None)
def _jit_quant_all_gather(mesh, axes, axis, ndim, fmt, gs):
    in_spec = [None] * ndim
    in_spec[axis] = axes
    in_spec = P(*in_spec)

    def _k(blk):
        return Q.quantized_all_gather(blk, axes, axis, fmt, gs)

    return jax.jit(jax.shard_map(_k, mesh=mesh, check_vma=False,
                                 in_specs=(in_spec, ), out_specs=P()))


@functools.lru_cache(maxsize=None)
def _jit_quant_reduce_scatter(mesh, axes, op, axis, ndim, fmt, gs, n):
    out_spec = [None] * ndim
    out_spec[axis] = axes
    out_spec = P(*out_spec)

    def _k(x):
        return Q.all_to_all_quant_reduce(x, axes, axis, n, wire_format=fmt,
                                         group_size=gs,
                                         mean=(op == ReduceOp.AVG))

    return jax.jit(jax.shard_map(_k, mesh=mesh, check_vma=False,
                                 in_specs=(P(), ), out_specs=out_spec))


@functools.lru_cache(maxsize=None)
def _jit_hier_quant_reduce_scatter(mesh, inner_axes, outer_axes, op, axis,
                                   ndim, fmt, gs, n_in, n_out):
    # inner-major tiling (see hierarchical_quant_reduce_scatter docstring)
    out_spec = [None] * ndim
    out_spec[axis] = inner_axes + outer_axes
    out_spec = P(*out_spec)

    def _k(x):
        return Q.hierarchical_quant_reduce_scatter(
            x, inner_axes, outer_axes, axis, n_in, n_out, wire_format=fmt,
            group_size=gs, mean=(op == ReduceOp.AVG))

    return jax.jit(jax.shard_map(_k, mesh=mesh, check_vma=False,
                                 in_specs=(P(), ), out_specs=out_spec))


_JIT_CACHES = (_jit_hier_all_reduce, _jit_quant_all_gather,
               _jit_quant_reduce_scatter, _jit_hier_quant_reduce_scatter)


def clear_jit_caches():
    """Drop cached executables so stale Mesh objects can be collected
    (called from ``dist.destroy_process_group``)."""
    for fn in _JIT_CACHES:
        fn.cache_clear()
    from .topology import clear_topology_caches
    clear_topology_caches()


# ----------------------------------------------------------- manual islands
def straight_through_constraint(x, sharding):
    """``with_sharding_constraint`` whose transpose is the identity.

    A plain constraint's VJP re-applies the same sharding to the cotangent
    — correct for values whose gradient shares their layout, but wrong on
    a quantized-gather island's *output*: the gathered param is replicated
    over the ZeRO axes while its cotangent is the still-unreduced gradient
    contribution, and constraining that replicated would force an eager
    all-reduce the backward scheduler should own (the same hazard
    ``overlap.mark_gather_tree`` documents).  Differentiated islands
    therefore enter/exit through this straight-through flavor."""

    @jax.custom_vjp
    def _st(v):
        return jax.lax.with_sharding_constraint(v, sharding)

    _st.defvjp(lambda v: (_st(v), None), lambda _, g: (g, ))
    return _st(x)


def gspmd_region(body, *, mesh, in_specs, out_specs, axis_names=None,
                 grad_transparent=False):
    """THE enter/exit contract for shrunken manual islands inside a GSPMD
    program (ISSUE 15, docs/zero.md "GSPMD-first ZeRO").

    A ``shard_map`` call is opaque to XLA's sharding propagation: layouts
    on either side of it are re-inferred, and a mismatch materializes as a
    silent reshard right where the island meets the surrounding program.
    This wrapper owns both boundaries: every operand is constrained to the
    island's expected ``PartitionSpec`` (``with_sharding_constraint`` —
    GSPMD materializes that layout *before* manual mode begins), the body
    runs under ``shard_map`` with exactly those specs, and every result is
    constrained on the way out so propagation resumes from a declared
    layout.  XLA's latency-hiding scheduler then treats the island as one
    schedulable op and slides independent compute around it — the reason
    the qwZ/qgZ islands exist at all (the codec needs bespoke bytes on the
    wire; everything else belongs to GSPMD).

    ``grad_transparent=True`` uses :func:`straight_through_constraint` for
    the boundary constraints — required when the island is differentiated
    (the qwZ gather), see that function's docstring.  ``axis_names``
    restricts manual mode to a subset of mesh axes (partial-manual; the
    caller owns the legacy-jax guard — ``jax_compat.is_legacy_shard_map``
    aborts on manual subgroups)."""
    from jax.sharding import NamedSharding

    def _is_multi(specs):
        # PartitionSpec subclasses tuple — a bare spec is ONE operand
        return isinstance(specs, (tuple, list)) and not isinstance(specs, P)

    in_t = tuple(in_specs) if _is_multi(in_specs) else (in_specs, )
    kw = dict(mesh=mesh, in_specs=in_t, out_specs=out_specs,
              check_vma=False)
    if axis_names is not None:
        kw["axis_names"] = frozenset(axis_names)
    inner = jax.shard_map(body, **kw)

    def constrain(x, spec):
        if spec is None:
            return x
        s = NamedSharding(mesh, spec)
        if grad_transparent:
            return straight_through_constraint(x, s)
        return jax.lax.with_sharding_constraint(x, s)

    def wrapped(*args):
        args = tuple(constrain(x, s) for x, s in zip(args, in_t))
        out = inner(*args)
        if _is_multi(out_specs):
            return tuple(constrain(o, s)
                         for o, s in zip(out, tuple(out_specs)))
        return constrain(out, out_specs)

    return wrapped


# ------------------------------------------------------------------- engine
#: ladder rung meaning "do not quantize this size band" — flat fp path
LADDER_FP = "fp32"


def build_wire_ladder(raw):
    """Normalize a ``wire_dtype_by_size`` value into an ascending tuple of
    ``(max_bytes, wire)`` rungs, or None when absent/empty (= global
    ``wire_dtype`` everywhere, the pre-ladder behavior).

    Accepts ``[max_bytes, wire]`` pairs or ``{"max_bytes":, "wire_dtype":}``
    dicts; ``max_bytes`` of null/None is the catch-all rung (at most one,
    necessarily last).  Rejects unknown wire formats, non-positive or
    duplicate bounds loudly — a mistyped ladder must never silently tune
    the wrong band."""
    if not raw:
        return None
    rungs = []
    for entry in raw:
        if isinstance(entry, dict):
            mb, wire = entry.get("max_bytes"), entry.get("wire_dtype")
        else:
            if len(entry) != 2:
                raise ValueError(
                    f"wire_dtype_by_size entry {entry!r} is not a "
                    "[max_bytes, wire_dtype] pair")
            mb, wire = entry
        if wire != LADDER_FP and wire not in Q.WIRE_FORMATS:
            raise ValueError(
                f"wire_dtype_by_size wire {wire!r} unknown "
                f"(have {LADDER_FP}, {', '.join(Q.WIRE_FORMATS)})")
        if mb is not None:
            mb = int(mb)
            if mb <= 0:
                raise ValueError(
                    f"wire_dtype_by_size max_bytes {mb} must be positive "
                    "(use null for the catch-all rung)")
        rungs.append((mb, str(wire)))
    bounded = [r for r in rungs if r[0] is not None]
    catchall = [r for r in rungs if r[0] is None]
    if len(catchall) > 1:
        raise ValueError("wire_dtype_by_size has multiple catch-all "
                         "(max_bytes: null) rungs")
    if len({mb for mb, _ in bounded}) != len(bounded):
        raise ValueError("wire_dtype_by_size has duplicate max_bytes bounds")
    bounded.sort(key=lambda r: r[0])
    return tuple(bounded + catchall)


def resolve_in_ladder(ladder, nbytes, default):
    """THE rung walk: first rung admitting ``nbytes`` wins (inclusive
    bounds, None = catch-all), ``default`` when the ladder is absent or
    every bounded rung is smaller.  Shared by the eager dispatch
    (:meth:`CollectivesEngine.resolve_wire_dtype`) and the ZeRO hot paths
    (``ZeroPartitionPlan.wire_for_size``) so rung semantics can never
    diverge between them."""
    if ladder is None:
        return default
    for bound, wire in ladder:
        if bound is None or nbytes <= bound:
            return wire
    return default


class CollectivesEngine:
    """Per-op variant selection over a duck-typed ``comm_optimizations``
    options object (the pydantic config model or
    :class:`~deepspeed_tpu.comm.collectives.config.CommOptimizations`)."""

    def __init__(self, opts=None):
        self.opts = opts if opts is not None else CommOptimizations()
        fmt = getattr(self.opts, "wire_dtype", "int8")
        if fmt not in Q.WIRE_FORMATS:
            raise ValueError(
                f"comm_optimizations.wire_dtype {fmt!r} unknown "
                f"(have {', '.join(Q.WIRE_FORMATS)})")
        self._ladder = build_wire_ladder(
            getattr(self.opts, "wire_dtype_by_size", None))

    def resolve_wire_dtype(self, nbytes):
        """Wire format for a payload of ``nbytes`` logical bytes: the first
        ladder rung that admits it, the global ``wire_dtype`` when the
        ladder is absent or every bounded rung is smaller.  May return
        ``"fp32"`` — the caller must fall through to the flat path."""
        return resolve_in_ladder(self._ladder, nbytes, self.opts.wire_dtype)

    @property
    def enabled(self):
        return bool(getattr(self.opts, "enabled", False))

    # ------------------------------------------------------------- helpers
    def _eligible(self, x):
        o = self.opts
        if not hasattr(x, "shape") or getattr(x, "ndim", 0) == 0:
            return False
        nbytes = x.size * x.dtype.itemsize
        return nbytes >= getattr(o, "min_message_size", 0)

    def _hierarchy(self, group):
        if not getattr(self.opts, "hierarchical_allreduce", False):
            return None
        return factor_group(group,
                            getattr(self.opts, "intra_node_size", 0))

    @staticmethod
    def _is_float(x):
        return jnp.issubdtype(x.dtype, jnp.floating)

    # ------------------------------------------------------------ dispatch
    def dispatch(self, op_name, x, group, reduce_op=ReduceOp.SUM, axis=0):
        """Offer ``x`` to the optimized variants.  Returns ``(result,
        variant, wire_bytes)`` or None (→ caller runs the flat path)."""
        if not self.enabled or group is None or not self._eligible(x):
            return None
        if op_name == "all_reduce":
            hit = self._all_reduce(x, group, reduce_op)
        elif op_name == "all_gather":
            hit = self._all_gather(x, group, axis)
        elif op_name == "reduce_scatter":
            hit = self._reduce_scatter(x, group, reduce_op, axis)
        else:
            hit = None
        if _telemetry.enabled:
            # per-variant pick counters: the autotuner's view of how often
            # each optimized path actually engages vs falls back flat
            variant = hit[1] if hit is not None else "flat_fallback"
            c = _telemetry.counter(f"comm/dispatch/{op_name}/{variant}",
                                   help="collectives-engine variant picks")
            if c is not None:
                c.inc()
        return hit

    def _all_reduce(self, x, group, op):
        if op not in _LINEAR_OPS:
            return None  # MIN/MAX/PRODUCT: flat passthrough, stays correct
        h = self._hierarchy(group)
        if h is None:
            return None
        # psum_scatter inside needs the per-rank block divisible by n_inner
        if x.shape[0] % (h.size * h.inner_size) != 0:
            return None
        fn = _jit_hier_all_reduce(h.mesh, h.inner_axes, h.outer_axes, op,
                                  h.size)
        # fp payload; the inter-node hop moves 1/n_inner of the data
        wire = (x.size * x.dtype.itemsize) // h.inner_size
        return fn(x), "hier", wire

    def _all_gather(self, x, group, axis):
        o = self.opts
        if not getattr(o, "quantized_weights", False) or \
                not self._is_float(x):
            return None
        n = group.size()
        if n <= 1 or x.shape[axis] % n != 0:
            return None
        fmt = self.resolve_wire_dtype(x.size * x.dtype.itemsize)
        if fmt == LADDER_FP:
            return None  # ladder says: this size band rides the flat path
        gs = getattr(o, "quantization_group_size", Q.DEFAULT_GROUP_SIZE)
        fn = _jit_quant_all_gather(group.mesh, group.axis_names, axis,
                                   x.ndim, fmt, gs)
        return fn(x), f"q_{fmt}", Q.quantized_wire_bytes(x.size, fmt, gs)

    def _reduce_scatter(self, x, group, op, axis):
        o = self.opts
        if not getattr(o, "quantized_gradients", False) or \
                op not in _LINEAR_OPS or not self._is_float(x):
            return None
        n = group.size()
        if n <= 1 or x.shape[axis] % n != 0:
            return None
        fmt = self.resolve_wire_dtype(x.size * x.dtype.itemsize)
        if fmt == LADDER_FP:
            return None  # ladder says: this size band rides the flat path
        gs = getattr(o, "quantization_group_size", Q.DEFAULT_GROUP_SIZE)
        h = self._hierarchy(group)
        if h is not None:
            fn = _jit_hier_quant_reduce_scatter(
                h.mesh, h.inner_axes, h.outer_axes, op, axis, x.ndim, fmt,
                gs, h.inner_size, h.outer_size)
            # quantized payload crosses DCN on 1/n_inner of the data
            wire = Q.quantized_wire_bytes(x.size // h.inner_size, fmt, gs)
            return fn(x), f"hier_q_{fmt}", wire
        fn = _jit_quant_reduce_scatter(group.mesh, group.axis_names, op,
                                       axis, x.ndim, fmt, gs, n)
        return fn(x), f"q_{fmt}", Q.quantized_wire_bytes(x.size, fmt, gs)
