"""Quantized collective primitives — the wire-compression layer.

Canonical home of the quantized collectives that ZeRO++ (qwZ/qgZ,
arxiv 2306.10209) and EQuARX (arxiv 2506.17615) describe: block-wise
quantize the payload, move int8/fp8 + per-group f32 scales instead of
bf16/f32, dequantize on arrival.  ``runtime/zero/zeropp.py`` re-exports
these for the manual-SPMD ZeRO paths; the eager
:class:`~deepspeed_tpu.comm.collectives.engine.CollectivesEngine` wraps them
in shard_map for the ``dist.*`` facade; ``benchmarks/comm_bench.py`` sweeps
them.

All functions here are **inside-shard_map** primitives: they take axis
names, operate on the local tile, and compose with
:mod:`deepspeed_tpu.comm.collectives.topology` hierarchies.  Codecs ride
``ops/pallas/quantizer.py`` (int) and ``ops/fp_quantizer.py`` (fp) — one
quantization kernel family for inference, ZeRO++ and the wire.
"""

from functools import partial
import math

import jax
import jax.numpy as jnp

from ...ops.pallas.quantizer import dequantize_blockwise, quantize_blockwise

DEFAULT_GROUP_SIZE = 2048
_LANES = 128  # scale-group granularity of the blockwise kernels

# wire formats: name → (quantize, dequantize) closures.  "int8"/"int4" ride
# the blockwise integer kernels; "fp8"/"fp6"/"fp12" the FP quantizer
# (reference csrc/fp_quantizer — fp6 packs 4 values → 3 bytes, so the
# all-gather volume drops to 3/8 of bf16).
_FP_FORMATS = {"fp8": (8, 3), "fp6": (6, 2), "fp12": (12, 7)}

# transported bytes per element for each wire format (int4 occupies int8
# storage on the simulated path — reported honestly, not as 0.5)
PAYLOAD_BYTES = {"int8": 1.0, "int4": 1.0, "fp8": 1.0, "fp6": 0.75,
                 "fp12": 1.5}

WIRE_FORMATS = tuple(PAYLOAD_BYTES)


def wire_codec(wire_format, group_size):
    """Wire format name → (quantize, dequantize) closure pair."""
    if wire_format in ("int8", "int4"):
        bits = 8 if wire_format == "int8" else 4
        quant = lambda x: quantize_blockwise(x, num_bits=bits,
                                             group_size=group_size,
                                             use_pallas=False)
        dequant = lambda q, s, m: dequantize_blockwise(q, s, m,
                                                       use_pallas=False)
        return quant, dequant
    if wire_format in _FP_FORMATS:
        from ...ops.fp_quantizer import dequantize_fp, quantize_fp
        bits, man = _FP_FORMATS[wire_format]
        quant = lambda x: quantize_fp(x, q_bits=bits, mantissa_bits=man,
                                      group_size=group_size, use_pallas=False)
        return quant, dequantize_fp
    raise ValueError(f"unknown wire format {wire_format!r} "
                     f"(have {', '.join(WIRE_FORMATS)})")


def effective_group_size(group_size):
    """The scale-group size the kernels actually use (lane-aligned, ≥128)."""
    return max(_LANES, group_size - group_size % _LANES)


def quantized_wire_bytes(n_elements, wire_format, group_size):
    """Actual transported bytes for a quantized payload of ``n_elements``:
    quantized values + one f32 scale per (lane-aligned) group.  This is what
    the comms logger / ds_bench report as wire size — NOT the logical fp
    tensor size.  ``"fp32"`` (a wire-ladder rung meaning "don't quantize")
    is the logical size: no scales travel."""
    if wire_format == "fp32":
        return int(n_elements) * 4
    gs = effective_group_size(group_size)
    groups = -(-n_elements // gs)
    return int(math.ceil(n_elements * PAYLOAD_BYTES[wire_format])) + groups * 4


# ------------------------------------------------------------ rowwise codec
# Per-row variant of the blockwise codecs, shared with the quantized paged-KV
# cache (inference/v2/kv_codec.py): one f32 scale per *leading index*, the
# group being the trailing ``reduce_axes`` axes (a token's [Hkv, Dh] K/V row).
# Same symmetric-absmax convention as the int8 wire codec above and the same
# e4m3fn saturation rule as ops/fp_quantizer — the ZeRO++ codec family, keyed
# so a paged scatter/gather can move scales alongside values.

ROWWISE_FORMATS = ("int8", "fp8")


def rowwise_storage_dtype(wire_format):
    """Element dtype a rowwise-quantized payload is stored as."""
    if wire_format == "int8":
        return jnp.int8
    if wire_format == "fp8":
        return jnp.float8_e4m3fn
    raise ValueError(f"unknown rowwise wire format {wire_format!r} "
                     f"(have {', '.join(ROWWISE_FORMATS)})")


def rowwise_codec(wire_format, reduce_axes=2):
    """Wire format name → (encode, decode) closures with per-row scales.

    ``encode(x)`` quantizes ``x[..., G1, G2]`` (the trailing ``reduce_axes``
    axes form the scale group) and returns ``(q, scale)`` where ``q`` has
    x's shape in the storage dtype and ``scale`` has the leading shape in
    f32.  ``decode(q, scale)`` returns f32 (accumulation never round-trips
    through the narrow dtype — same rule as all_to_all_quant_reduce)."""
    ax = tuple(range(-reduce_axes, 0))
    if wire_format == "int8":
        qmax = 127.0
        store = lambda y: jnp.clip(jnp.round(y), -qmax, qmax).astype(jnp.int8)
    elif wire_format == "fp8":
        # native e4m3fn: clamp before the cast — the "fn" encoding has no
        # inf, overflow lands on NaN (same guard as ops/fp_quantizer)
        qmax = float(jnp.finfo(jnp.float8_e4m3fn).max)  # 448
        store = lambda y: jnp.clip(y, -qmax, qmax).astype(jnp.float8_e4m3fn)
    else:
        raise ValueError(f"unknown rowwise wire format {wire_format!r} "
                         f"(have {', '.join(ROWWISE_FORMATS)})")

    def encode(x):
        xf = x.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf), axis=ax, keepdims=True)
        scale = jnp.where(absmax == 0.0, 1.0, absmax / qmax)
        return store(xf / scale), jnp.squeeze(scale, axis=ax)

    def decode(q, scale):
        return q.astype(jnp.float32) * scale.reshape(scale.shape
                                                     + (1, ) * reduce_axes)

    return encode, decode


def quantized_all_gather(x, ax_names, dim, wire_format="int8",
                         group_size=DEFAULT_GROUP_SIZE):
    """Inside-shard_map: quantize-gather the local tile along mesh axes
    ``ax_names``, reassembling the full dim in axis-index order (matches GSPMD
    tiling order).  The wire payload is quantized values + one f32 scale per
    ``group_size`` elements (reference qwZ, csrc/quantization/quantize.cu;
    fp formats via csrc/fp_quantizer analog).

    ``wire_format="fp32"`` (the wire ladder's "don't quantize this size
    band" rung, docs/autotuning.md) keeps the identical gather schedule
    with the raw fp payload — bit-exact, so a per-size ladder can route
    latency-bound leaves flat without changing placement semantics."""
    if wire_format == "fp32":
        parts = jax.lax.all_gather(x, ax_names)
        return jnp.concatenate(list(parts), axis=dim)
    quant, dequant = wire_codec(wire_format, group_size)
    q, s, meta = quant(x)
    qg = jax.lax.all_gather(q, ax_names)
    sg = jax.lax.all_gather(s, ax_names)
    parts = jax.vmap(lambda qq, ss: dequant(qq, ss, meta))(qg, sg)
    return jnp.concatenate(list(parts), axis=dim)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def qdq_all_gather_st(x, ax_names, dim, wire_format, group_size):
    """Straight-through quantized gather: forward is the quantized gather;
    backward is the exact VJP of a plain all-gather (reduce-scatter of the
    cotangent) — the quantization rounding must not zero the gradient."""
    return quantized_all_gather(x, ax_names, dim, wire_format, group_size)


def _qdq_fwd(x, ax_names, dim, wire_format, group_size):
    return qdq_all_gather_st(x, ax_names, dim, wire_format, group_size), None


def _qdq_bwd(ax_names, dim, wire_format, group_size, _, dy):
    return (jax.lax.psum_scatter(dy, ax_names, scatter_dimension=dim,
                                 tiled=True), )


qdq_all_gather_st.defvjp(_qdq_fwd, _qdq_bwd)


def quantized_all_to_all(x, ax_names, split_axis, concat_axis, n,
                         wire_format="int8", group_size=DEFAULT_GROUP_SIZE):
    """Inside-shard_map: *permuting* quantized all-to-all — rank i sends
    chunk j of ``split_axis`` to rank j and concatenates what it receives
    along ``concat_axis``.  This is the expert-dispatch exchange (reference
    ``_AllToAll``, moe/sharded_moe.py:23): unlike
    :func:`all_to_all_quant_reduce` nothing is summed — each rank's
    capacity block survives verbatim, just on a quantized wire.

    ``wire_format="fp32"`` keeps the identical exchange with the raw fp
    payload (the wire ladder's flat rung) — bit-exact."""
    if wire_format == "fp32":
        return jax.lax.all_to_all(x, ax_names, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)
    quant, dequant = wire_codec(wire_format, group_size)
    chunks = jnp.stack(jnp.split(x, n, axis=split_axis))  # [n, ...chunk]
    _, _, meta = quant(chunks[0])
    q, s = jax.vmap(lambda c: quant(c)[:2])(chunks)
    qx = jax.lax.all_to_all(q, ax_names, split_axis=0, concat_axis=0)
    sx = jax.lax.all_to_all(s, ax_names, split_axis=0, concat_axis=0)
    parts = jax.vmap(lambda qq, ss: dequant(qq, ss, meta))(qx, sx)
    return jnp.concatenate(list(parts), axis=concat_axis).astype(x.dtype)


def all_to_all_quant_reduce(g, ax_names, dim, n, num_bits=8,
                            group_size=DEFAULT_GROUP_SIZE, wire_format=None,
                            mean=True):
    """Inside-shard_map: quantized reduce-scatter of a (replicated) gradient:
    split along ``dim`` into ``n`` partitions, quantized all-to-all so rank i
    receives every rank's partition i, dequantize and reduce in fp32.
    Returns this rank's partition — the mean over ranks by default, the sum
    with ``mean=False`` (reference ``all_to_all_quant_reduce``,
    runtime/comm/coalesced_collectives.py:31 — single-hop on ICI, see
    ``runtime/zero/zeropp.py`` module docstring).

    ``wire_format="fp32"`` (the wire ladder's "don't quantize this size
    band" rung) keeps the identical split/all-to-all/sum schedule and
    output placement with the raw fp payload — no codec, no grid error."""
    fmt = wire_format or ("int8" if num_bits == 8 else "int4")
    if fmt == "fp32":
        chunks = jnp.stack(jnp.split(g, n, axis=dim))
        parts = jax.lax.all_to_all(chunks, ax_names, split_axis=0,
                                   concat_axis=0)
        out = jnp.sum(parts.astype(jnp.float32), axis=0)
        return out / n if mean else out
    quant, dequant = wire_codec(fmt, group_size)
    chunks = jnp.stack(jnp.split(g, n, axis=dim))  # [n, ...chunk]
    _, _, meta = quant(chunks[0])
    # dequantize straight to f32 so accumulation never round-trips through a
    # narrow source dtype
    meta = (meta[0], jnp.float32) + tuple(meta[2:])
    q, s = jax.vmap(lambda c: quant(c)[:2])(chunks)
    qx = jax.lax.all_to_all(q, ax_names, split_axis=0, concat_axis=0)
    sx = jax.lax.all_to_all(s, ax_names, split_axis=0, concat_axis=0)
    parts = jax.vmap(lambda qq, ss: dequant(qq, ss, meta))(qx, sx)
    out = jnp.sum(parts.astype(jnp.float32), axis=0)
    return out / n if mean else out


def hierarchical_quant_reduce_scatter(g, inner_axes, outer_axes, dim,
                                      n_inner, n_outer, wire_format="int8",
                                      group_size=DEFAULT_GROUP_SIZE,
                                      mean=True):
    """Inside-shard_map 2-hop qgZ: full-precision reduce-scatter over the
    intra-node ``inner_axes`` (ICI — cheap, full data), then quantized
    all-to-all reduce over the inter-node ``outer_axes`` on 1/n_inner of the
    data (DCN — one quantization error on the slow hop only; reference qgZ,
    ZeRO++ §4.3, minus the NCCL swizzle which mesh axes make unnecessary).

    Tiling order of the result along ``dim`` is **inner-major**: rank
    (outer=o, inner=i) holds chunk ``i * n_outer + o`` — callers declaring
    shard_map out_specs must list ``inner_axes + outer_axes`` on that dim.
    """
    part = g
    for a in inner_axes:
        part = jax.lax.psum_scatter(part, a, scatter_dimension=dim,
                                    tiled=True)
    out = all_to_all_quant_reduce(part, outer_axes, dim, n_outer,
                                  wire_format=wire_format,
                                  group_size=group_size, mean=False)
    if mean:
        # psum_scatter already summed over inner, the a2a over outer
        out = out / (n_inner * n_outer)
    return out
