"""Runtime-independent view of the ``comm_optimizations`` config block.

The JSON-schema'd pydantic model lives with the rest of the config system
(``runtime/config.py:CommOptimizationsConfig``); this dataclass carries the
same fields with the same defaults for standalone consumers (benchmarks,
tests, tools) that must not drag the full runtime config machinery in.  The
engine itself is duck-typed — either object works.
"""

from dataclasses import dataclass, field

from .quantized import DEFAULT_GROUP_SIZE


@dataclass
class Prefetch:
    """Forward-direction ZeRO-3 param-gather prefetch knobs (see
    ``runtime/zero/overlap.py`` / docs/overlap.md forward-prefetch
    section).  Own enable gate, independent of ``Overlap.enabled``."""
    enabled: bool = False
    # bucket payload bound in MiB; 0 = the 32 MiB overlap default (the
    # config layer stamps this from stage3_prefetch_bucket_size when that
    # reference knob armed the prefetch)
    bucket_mb: float = 0.0
    # max buckets with their all-gather outstanding; clamped per model by
    # stage3_max_live_parameters
    max_inflight: int = 2


@dataclass
class Overlap:
    """Bucketed backward-pass gradient-reduction scheduler knobs (see
    ``runtime/zero/overlap.py`` / docs/overlap.md).  Own enable gate:
    bucketing changes when reduces run, not what they carry."""
    enabled: bool = False
    # bucket size bound in MiB of gradient payload (fractional ok)
    bucket_mb: float = 32.0
    # manual qgZ path: max buckets with the inter-node hop outstanding
    max_inflight: int = 2
    # forward-direction stage-3 param-gather prefetch
    prefetch: Prefetch = field(default_factory=Prefetch)


@dataclass
class CommOptimizations:
    """See docs/collectives.md for the knob-by-knob story."""
    enabled: bool = False
    # hierarchical (intra-node → inter-node → intra-node) all-reduce and the
    # 2-hop quantized reduce-scatter; engages only when a topology hierarchy
    # exists (multi-axis group, TPU slice boundary, or intra_node_size)
    hierarchical_allreduce: bool = True
    # quantize all-gather payloads (ZeRO++ qwZ-style wire compression)
    quantized_weights: bool = False
    # quantize reduce-scatter payloads (ZeRO++ qgZ-style)
    quantized_gradients: bool = False
    # wire format for quantized payloads: int8 | int4 | fp8 | fp6 | fp12
    wire_dtype: str = "int8"
    # per-message-size wire-format ladder (EQuARX: the optimal quantization
    # varies by message size).  List of [max_bytes, wire] rungs, ascending;
    # a payload of n logical bytes takes the first rung with n <= max_bytes
    # (null/None max_bytes = catch-all), sizes above every rung fall back to
    # the global ``wire_dtype``.  "fp32" as a rung wire means "do not
    # quantize this size band" (flat path).  None/absent (default) keeps
    # the global ``wire_dtype`` for every size — bit-identical to the
    # pre-ladder engine.  Emitted by the autotuner (docs/autotuning.md).
    wire_dtype_by_size: list = None
    # elements per quantization scale group (lane-aligned down to ≥128)
    quantization_group_size: int = DEFAULT_GROUP_SIZE
    # devices per node for the hierarchy split; 0 = auto-detect from device
    # metadata (slice/process boundaries) or DS_TPU_INTRA_NODE_SIZE
    intra_node_size: int = 0
    # tensors smaller than this many bytes always take the flat path
    # (latency-bound regime — quantize/hierarchy overhead beats the savings)
    min_message_size: int = 0
    # micro-step architecture for the qgZ training path: "gspmd" (default,
    # the GSPMD-first micro with quantized islands — docs/zero.md) or
    # "flat_manual" (force the legacy full-manual shard_map micro; the
    # ds_bench --zero-mode baseline lane)
    zero_mode: str = "gspmd"
    # bucketed backward-pass gradient-reduction scheduler
    overlap: Overlap = field(default_factory=Overlap)
