"""Topology-aware quantized collectives engine (see docs/collectives.md).

Layers:

* :mod:`.topology` — factorize mesh-axis groups into (inter-node,
  intra-node) hierarchies;
* :mod:`.quantized` — inside-shard_map quantized/hierarchical primitives
  shared with the ZeRO++ runtime paths;
* :mod:`.engine` — per-op variant selection behind the ``dist.*`` facade;
* :mod:`.config` — runtime-independent ``comm_optimizations`` options.
"""

from .config import CommOptimizations
from .engine import (LADDER_FP, CollectivesEngine, build_wire_ladder,
                     clear_jit_caches, resolve_in_ladder)
from .quantized import (DEFAULT_GROUP_SIZE, WIRE_FORMATS,
                        all_to_all_quant_reduce, effective_group_size,
                        hierarchical_quant_reduce_scatter,
                        quantized_all_gather, quantized_all_to_all,
                        quantized_wire_bytes, wire_codec)
from .topology import (Hierarchy, axis_intra_size, detect_intra_node_size,
                       factor_group, split_mesh)
