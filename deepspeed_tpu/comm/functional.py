"""Axis-name collectives for use inside jit/shard_map regions.

These are the traced-context counterpart of the eager facade in
``deepspeed_tpu.comm``: thin wrappers over ``jax.lax`` collectives that take a
mesh *axis name* (or tuple) instead of a group object.  Ulysses attention, MoE
dispatch, pipeline p2p, and pallas-adjacent code call these; XLA lowers them to
ICI/DCN collectives.

The reference's analog is calling ``deepspeed.comm`` collectives on tensors
inside the hot loop (e.g. ``sequence/layer.py:182``, ``runtime/pipe/p2p.py:46``)
— here the hot loop is traced once, so these are ordinary lax primitives.
"""

import jax
import jax.numpy as jnp


def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name):
    return jax.lax.pmax(x, axis_name)


def pmin(x, axis_name):
    return jax.lax.pmin(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


def send_next_recv_prev(x, axis_name, size):
    """Pipeline p2p: shift ``x`` to the next rank along ``axis_name`` (ring).

    Analog of reference ``runtime/pipe/p2p.py:46 send``/``:67 recv`` between
    adjacent pipeline stages — on TPU this is a collective-permute that XLA
    maps to neighbor ICI hops.
    """
    perm = [(i, (i + 1) % size) for i in range(size)]
    return jax.lax.ppermute(x, axis_name, perm)


def send_prev_recv_next(x, axis_name, size):
    perm = [(i, (i - 1) % size) for i in range(size)]
    return jax.lax.ppermute(x, axis_name, perm)
