"""deepspeed_tpu.comm — the communication facade (L2).

API mirrors the reference's torch.distributed-shaped facade
(``deepspeed/comm/comm.py:13-19``): ``init_distributed``, ``all_reduce``,
``all_gather``, ``reduce_scatter``, ``all_to_all``, ``broadcast``, ``barrier``,
``get_rank``/``get_world_size``, plus ``initialize_mesh_device``.  The single
backend is :class:`deepspeed_tpu.comm.backend.MeshBackend`; groups are mesh-axis
subsets (``new_group`` accepts axis names, not arbitrary rank lists).

Every collective is wrapped by ``timed_op`` feeding the ``CommsLogger``
(reference ``comm/comm.py:101 @timed_op``).
"""

import functools
import os
import time

import numpy as np

from .backend import MeshBackend, ProcessGroup
from .reduce_op import ReduceOp
from .. import telemetry as _telemetry
from ..utils.comms_logging import CommsLogger, get_msg_size_from_args
from ..utils.logging import logger

cdb = None  # current distributed backend (reference comm/comm.py:41)
comms_logger = CommsLogger()

_COMM_CONFIGURED = False

# Installed collectives engine (comm/collectives/) — None = every op takes
# the flat backend path, bit-identical to the pre-engine facade.
_engine = None
# (variant, wire_bytes) of the most recent dispatched collective, consumed
# by timed_op so the comms logger reports transported (post-quantization)
# bytes and the variant name.
_last_dispatch = None


def is_initialized():
    return cdb is not None and cdb.initialized


def _assert_initialized():
    if not is_initialized():
        init_distributed()


def set_collectives_engine(engine):
    """Install (or with None, remove) the pluggable collectives engine that
    ``_dispatch`` offers every eager collective to."""
    global _engine
    _engine = engine


def get_collectives_engine():
    return _engine


def configure(config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None,
              debug=None):
    """Configure comms logging + collectives engine (reference
    ``comm/comm.py`` configure; the engine half is the TPU addition)."""
    if config is not None and getattr(config, "comms_config", None) is not None:
        comms_logger.configure(config.comms_config)
    if config is not None and getattr(
            config, "comm_optimizations_config", None) is not None:
        co = config.comm_optimizations_config
        if getattr(co, "enabled", False):
            from .collectives import CollectivesEngine
            set_collectives_engine(CollectivesEngine(co))
        else:
            set_collectives_engine(None)
    if enabled is not None:
        comms_logger.enabled = enabled
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops
    if verbose is not None:
        comms_logger.verbose = verbose
    if debug is not None:
        comms_logger.debug = debug


def timed_op(func):
    import inspect
    sig = inspect.signature(func)

    @functools.wraps(func)
    def wrapper(*args, log_name=None, **kwargs):
        global _last_dispatch
        name = log_name or func.__name__
        should_log = comms_logger.enabled and (
            comms_logger.prof_all or name in comms_logger.prof_ops)
        tel_on = _telemetry.enabled
        if not should_log and not tel_on:
            return func(*args, **kwargs)
        _last_dispatch = None
        t0 = time.perf_counter()
        result = func(*args, **kwargs)
        if comms_logger.sync_timing or (
                tel_on and _telemetry.get_recorder() is not None
                and _telemetry.get_recorder().fence):
            # opt-in: precise completion latency at the cost of serializing
            # the async pipeline (round-1 review item 9 — no longer default;
            # telemetry fence mode wants the same truth for exposed-comm)
            try:
                result.block_until_ready()
            except Exception:
                pass
        latency = time.perf_counter() - t0
        # Bind args so a positionally-passed group is still found.
        bound = sig.bind_partial(*args, **kwargs).arguments
        x = bound.get("tensor", args[0] if args else None)
        msg_size = get_msg_size_from_args(x) if x is not None else 0
        group = bound.get("group")
        ws = group.size() if group is not None else (cdb.world_size() if cdb else 1)
        variant, wire = _last_dispatch if _last_dispatch else (None, None)
        if should_log:
            comms_logger.append(func.__name__, name, latency, msg_size, ws,
                                wire_size=wire, variant=variant)
        if tel_on:
            # same wire-truthful record, joined into the step trace — the
            # exposed-comm-fraction and per-variant latency feed
            _telemetry.record_comm_event(name, variant, msg_size, wire,
                                         latency, ws)
        return result

    return wrapper


_jax_distributed_up = False


def mpi_discovery(distributed_port=29500):
    """Derive (coordinator, num_processes, process_id) from MPI or SLURM
    env (reference ``comm/comm.py:688 mpi_discovery`` + the SLURM path of
    the launcher).  Returns None when neither launcher's env is present.

    * ``mpirun``: OMPI_COMM_WORLD_RANK/SIZE; the coordinator address is
      broadcast via mpi4py when available, else COORDINATOR_ADDRESS must be
      exported (``mpirun -x COORDINATOR_ADDRESS=host0:port``).
    * SLURM: SLURM_PROCID/SLURM_NPROCS + the first node of
      SLURM_STEP_NODELIST (simple "prefix[a-b]" expansion).
    """
    env = os.environ
    if "OMPI_COMM_WORLD_RANK" in env:
        pid = int(env["OMPI_COMM_WORLD_RANK"])
        nproc = int(env["OMPI_COMM_WORLD_SIZE"])
        coord = env.get("COORDINATOR_ADDRESS")
        if coord is None:
            try:
                from mpi4py import MPI
                comm = MPI.COMM_WORLD
                import socket
                # broadcast the bare hostname — gethostbyname often
                # resolves to 127.0.1.1 on stock images, which remote
                # ranks cannot reach; let each rank resolve it via DNS
                coord = comm.bcast(
                    f"{socket.gethostname()}:{distributed_port}", root=0)
            except ImportError as e:
                raise RuntimeError(
                    "launched under mpirun but mpi4py is unavailable to "
                    "broadcast the coordinator — export "
                    "COORDINATOR_ADDRESS=<rank0-host>:<port> "
                    "(e.g. mpirun -x COORDINATOR_ADDRESS=...)") from e
        return coord, nproc, pid
    if "SLURM_PROCID" in env and "SLURM_NPROCS" in env:
        pid = int(env["SLURM_PROCID"])
        nproc = int(env["SLURM_NPROCS"])
        coord = env.get("COORDINATOR_ADDRESS")
        if coord is None:
            nodelist = env.get("SLURM_STEP_NODELIST",
                               env.get("SLURM_NODELIST", ""))
            first = nodelist.split(",")[0]
            if "[" in first:  # "prefix[3-8]" or "prefix[3,9]" → prefix3
                prefix, rng = first.split("[", 1)
                first = prefix + rng.split("-")[0].split(",")[0].rstrip("]")
            if not first:
                raise RuntimeError(
                    "SLURM env present but no node list — export "
                    "COORDINATOR_ADDRESS=<rank0-host>:<port>")
            coord = f"{first}:{distributed_port}"
        return coord, nproc, pid
    return None


def ensure_runtime_initialized(auto_mpi_discovery=True,
                               distributed_port=29500):
    """The multi-process half of ``init_distributed``: bring up
    ``jax.distributed`` (COORDINATOR_ADDRESS rendezvous — the MASTER_ADDR
    analog) WITHOUT touching the mesh.  MUST run before anything asks jax
    for devices, else the backend initializes single-process and the global
    device view never federates.  Idempotent."""
    global _jax_distributed_up
    if _jax_distributed_up:
        return
    coord = os.environ.get("COORDINATOR_ADDRESS")
    nproc = int(os.environ.get("JAX_PROCESS_COUNT",
                               os.environ.get("WORLD_SIZE", "1")))
    pid = int(os.environ.get("JAX_PROCESS_ID", os.environ.get("RANK", "0")))
    if nproc <= 1 and auto_mpi_discovery:
        # launched by mpirun/srun directly (reference auto_mpi_discovery);
        # an exported COORDINATOR_ADDRESS is respected, MPI env supplies
        # the rank/size our launcher vars would have
        discovered = mpi_discovery(distributed_port=distributed_port)
        if discovered is not None:
            coord, nproc, pid = discovered
    if coord is not None and nproc > 1:
        import jax
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid)
        logger.info(
            f"jax.distributed initialized: process {pid}/{nproc} @ {coord}")
    _jax_distributed_up = True


def init_distributed(dist_backend=None, auto_mpi_discovery=True,
                     distributed_port=29500, verbose=True, timeout=None,
                     init_method=None, dist_init_required=None, config=None,
                     rank=-1, world_size=-1, mesh=None):
    """Bring up the distributed runtime + global mesh backend.

    Analog of reference ``comm/comm.py:619 init_distributed``: on multi-host
    TPU pods this calls ``jax.distributed.initialize`` (rendezvous via
    ``COORDINATOR_ADDRESS``/env set by the launcher, the MASTER_ADDR analog);
    single-host it just builds the mesh over local devices.
    """
    global cdb
    if is_initialized():
        # already up: still honor a (re)supplied config — otherwise a world
        # initialized before deepspeed_tpu.initialize() would silently drop
        # comms-logger settings and never install the collectives engine
        if config is not None:
            configure(config=config)
        return cdb

    ensure_runtime_initialized(auto_mpi_discovery=auto_mpi_discovery,
                               distributed_port=distributed_port)

    from ..accelerator import get_accelerator
    backend_name = dist_backend or get_accelerator().communication_backend_name()
    from ..utils import groups as groups_mod
    if mesh is None:
        if not groups_mod.mesh_is_initialized():
            groups_mod.initialize_mesh()
        mesh = groups_mod.get_global_mesh()
    cdb = MeshBackend(mesh=mesh, name=backend_name)
    if config is not None:
        configure(config=config)
    return cdb


def initialize_mesh_device(mesh_shape, mesh_axis_names=None):
    """Reference ``comm/comm.py:603`` — build the (dp, sp, ...) mesh explicitly."""
    global cdb
    from ..utils import groups as groups_mod
    if mesh_axis_names is None:
        mesh_axis_names = ("dp", "sp")[:len(mesh_shape)]
    known = {"dp", "sp", "pp", "tp"}
    unknown = set(mesh_axis_names) - known
    if unknown:
        raise ValueError(f"unknown mesh axis names {sorted(unknown)}; "
                         f"supported: {sorted(known)}")
    sizes = dict(zip(mesh_axis_names, mesh_shape))
    st = groups_mod.initialize_mesh(dp=sizes.get("dp"), sp=sizes.get("sp", 1),
                                    pp=sizes.get("pp", 1), tp=sizes.get("tp", 1))
    if cdb is not None:
        cdb.mesh = st.mesh
        cdb.world_group = ProcessGroup(st.mesh, st.mesh.axis_names)
    return st.mesh


def get_world_group():
    _assert_initialized()
    return cdb.world_group


def new_group(axis_names, mesh=None):
    """Group = mesh-axis subset. ``new_group(("dp",))`` etc."""
    _assert_initialized()
    return ProcessGroup(mesh or cdb.mesh, axis_names)


def get_rank(group=None):
    if not is_initialized():
        return int(os.environ.get("RANK", "0"))
    return cdb.rank()


def get_world_size(group=None):
    if not is_initialized():
        return int(os.environ.get("WORLD_SIZE", "1"))
    if group is not None:
        return group.size()
    return cdb.world_size()


def get_local_rank():
    return int(os.environ.get("LOCAL_RANK", "0"))


# ------------------------------------------------------------------ collectives
def _dispatch(op_name, tensor, op=ReduceOp.SUM, group=None, axis=0):
    """THE dispatch point: every eager collective (and the ``allgather_fn``/
    ``reduce_scatter_fn``/``*_coalesced`` helpers riding the public ops) is
    offered to the installed collectives engine first; None / no-hit falls
    through to the flat MeshBackend path — bit-identical to the engine-less
    facade."""
    global _last_dispatch
    # reset HERE, not only in timed_op: a variant hit recorded by an
    # unlogged op must never be attributed to a later flat/fallback op —
    # that mislabels the op AND double-counts the quantized wire bytes
    _last_dispatch = None
    eng = _engine
    if eng is not None and eng.enabled:
        g = group if group is not None else cdb.world_group
        hit = eng.dispatch(op_name, tensor, g, reduce_op=op, axis=axis)
        if hit is not None:
            result, variant, wire = hit
            _last_dispatch = (variant, wire)
            return result
    if op_name == "all_reduce":
        return cdb.all_reduce(tensor, op=op, group=group)
    if op_name == "all_gather":
        return cdb.all_gather(tensor, group=group, axis=axis)
    if op_name == "reduce_scatter":
        return cdb.reduce_scatter(tensor, op=op, group=group, axis=axis)
    raise ValueError(f"unknown collective {op_name!r}")


@timed_op
def all_reduce(tensor, op=ReduceOp.SUM, group=None, async_op=False):
    _assert_initialized()
    return _dispatch("all_reduce", tensor, op=op, group=group)


@timed_op
def all_gather(tensor, group=None, axis=0, async_op=False):
    _assert_initialized()
    return _dispatch("all_gather", tensor, group=group, axis=axis)


# torch.distributed-parity alias (reference has all_gather_into_tensor)
all_gather_into_tensor = all_gather


@timed_op
def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, axis=0, async_op=False):
    _assert_initialized()
    return _dispatch("reduce_scatter", tensor, op=op, group=group, axis=axis)


reduce_scatter_tensor = reduce_scatter


@timed_op
def all_to_all_single(tensor, group=None, split_axis=0, concat_axis=0, async_op=False):
    _assert_initialized()
    return cdb.all_to_all(tensor, group=group, split_axis=split_axis,
                          concat_axis=concat_axis)


all_to_all = all_to_all_single


@timed_op
def broadcast(tensor, src=0, group=None, async_op=False):
    _assert_initialized()
    return cdb.broadcast(tensor, src=src, group=group)


def barrier(group=None):
    _assert_initialized()
    return cdb.barrier(group=group)


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    """Reference ``monitored_barrier``: a barrier that reports how long the
    sync took (straggler visibility; there is no per-rank blame to assign
    under a single SPMD controller)."""
    t0 = time.perf_counter()
    out = barrier(group=group)
    dt = time.perf_counter() - t0
    if timeout is not None and dt > float(timeout):
        logger.warning(f"monitored_barrier took {dt:.3f}s "
                       f"(timeout {timeout}s)")
    return out


@timed_op
def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, async_op=False):
    """Reference ``reduce``: under SPMD the reduced value is computed
    everywhere (an all_reduce); ``dst`` has no special placement."""
    _assert_initialized()
    return _dispatch("all_reduce", tensor, op=op, group=group)


@timed_op
def gather(tensor, gather_list=None, dst=0, group=None, axis=0,
           async_op=False):
    """Reference ``gather``: SPMD computes the gathered result everywhere
    (an all_gather); ``dst``/``gather_list`` have no special placement."""
    _assert_initialized()
    return _dispatch("all_gather", tensor, group=group, axis=axis)


# reference inference_all_reduce: same collective, inference-tagged
inference_all_reduce = all_reduce


def all_gather_coalesced(tensors, group=None, async_op=False):
    """Reference coalesced all-gather: one call per tensor (XLA already
    fuses adjacent collectives under jit; eager coalescing buys nothing).
    Rides ``all_gather`` and therefore the engine dispatch point — a
    coalesced list gets the same quantized/hierarchical variants per
    tensor."""
    return [all_gather(t, group=group) for t in tensors]


def all_reduce_coalesced(tensors, op=ReduceOp.SUM, group=None,
                         async_op=False):
    return [all_reduce(t, op=op, group=group) for t in tensors]


def allgather_fn(output_tensor, input_tensor, group=None, async_op=False,
                 debug=None):
    """Reference helper (picks the best all-gather impl): the pick happens
    at the single ``_dispatch`` point inside ``all_gather`` — flat,
    quantized, or hierarchical per the installed engine; the output-buffer
    arg has no meaning without torch's in-place semantics."""
    return all_gather(input_tensor, group=group)


def reduce_scatter_fn(output_tensor, input_tensor, op=ReduceOp.SUM,
                      group=None, async_op=False, debug=None):
    return reduce_scatter(input_tensor, op=op, group=group)


def send(tensor, dst, group=None, tag=0):
    raise NotImplementedError(
        "eager decoupled send/recv does not exist under a single SPMD "
        "controller — express hot-path p2p as lax.ppermute inside the "
        "compiled program (see runtime/pipe/engine.py); for host-side "
        "control-plane traffic use dist.send_obj / dist.recv_obj")


def recv(tensor, src, group=None, tag=0):
    raise NotImplementedError(
        "eager decoupled send/recv does not exist under a single SPMD "
        "controller — express hot-path p2p as lax.ppermute inside the "
        "compiled program (see runtime/pipe/engine.py); for host-side "
        "control-plane traffic use dist.send_obj / dist.recv_obj")


isend = send
irecv = recv


# ------------------------------------------- out-of-band object p2p
# Reference ``runtime/pipe/p2p.py:46`` (send_obj/recv_obj): a host-side
# control-plane channel for debugging/elastic tooling — NOT the activation
# hot path (that is ppermute inside the compiled program).  Multi-process:
# rides the jax.distributed coordination service's KV store; single
# process: an in-memory queue.
_obj_queues = {}        # (src, dst, tag) → list of payloads (1-process)
_obj_send_seq = {}      # (dst, tag) → next sequence number
_obj_recv_seq = {}      # (src, tag) → next sequence number


def _kv_client():
    try:
        from jax._src.distributed import global_state
        return global_state.client
    except Exception:
        return None


def send_obj(obj, dst, tag=0):
    """Send a picklable object to process ``dst`` (reference
    ``pipe/p2p.py`` ``send_obj``).  Non-blocking-ish: the payload is posted
    to the coordination-service KV store and consumed by ``recv_obj``."""
    import base64
    import pickle
    me = get_rank()
    seq = _obj_send_seq.get((dst, tag), 0)
    _obj_send_seq[(dst, tag)] = seq + 1
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    client = _kv_client()
    if client is None or get_world_size() == 1:
        _obj_queues.setdefault((me, dst, tag), []).append(payload)
        return
    client.key_value_set(f"ds_p2p/{me}->{dst}/t{tag}/s{seq}",
                         base64.b64encode(payload).decode("ascii"))


def recv_obj(src, tag=0, timeout_s=300):
    """Blocking receive of the next object from process ``src``."""
    import base64
    import pickle
    me = get_rank()
    seq = _obj_recv_seq.get((src, tag), 0)
    _obj_recv_seq[(src, tag)] = seq + 1
    client = _kv_client()
    if client is None or get_world_size() == 1:
        q = _obj_queues.get((src, me, tag))
        if not q:
            raise RuntimeError(
                f"recv_obj: nothing sent from rank {src} (tag {tag})")
        return pickle.loads(q.pop(0))
    key = f"ds_p2p/{src}->{me}/t{tag}/s{seq}"
    val = client.blocking_key_value_get(key, timeout_s * 1000)
    try:
        # consumed: free the coordinator's copy (payloads can be MBs; a
        # long-running elastic loop would otherwise leak every message)
        client.key_value_delete(key)
    except Exception:
        pass
    return pickle.loads(base64.b64decode(val))


def scatter(tensor, scatter_list=None, src=0, group=None, async_op=False):
    raise NotImplementedError(
        "eager scatter has no SPMD analog — feed per-shard data with "
        "engine.shard_batch / jax.device_put with a sharding instead")


# ------------------------------------------------------- capability probes
def is_available():
    return True


def has_all_gather_into_tensor():
    return True


def has_reduce_scatter_tensor():
    return True


def has_all_reduce_coalesced():
    return True


def has_coalescing_manager():
    return False  # XLA fuses under jit; no eager coalescing manager


def _group_member_devices(group):
    """Devices of ONE instance of a mesh-axis group (the slice at index 0
    of every non-group axis — under a single SPMD controller there is no
    'caller rank' to select a specific instance; all instances are
    isomorphic)."""
    g = group if group is not None else cdb.world_group
    mesh = getattr(g, "mesh", cdb.mesh)
    axes = set(getattr(g, "axis_names", mesh.axis_names))
    idx = tuple(slice(None) if name in axes else 0
                for name in mesh.axis_names)
    return list(np.asarray(mesh.devices)[idx].flat)


def get_global_rank(group=None, group_rank=0):
    """Reference ``get_global_rank``: global device id of the group's
    ``group_rank``-th member."""
    _assert_initialized()
    devices = _group_member_devices(group)
    if not 0 <= group_rank < len(devices):
        raise IndexError(
            f"group_rank {group_rank} out of range for group of "
            f"size {len(devices)}")
    return devices[group_rank].id


def get_all_ranks_from_group(group=None):
    _assert_initialized()
    return [d.id for d in _group_member_devices(group)]


def log_summary(show_straggler=False):
    """Reference ``comm/comm.py:422`` — dump the comms logger table."""
    return comms_logger.log_all(print_log=True, show_straggler=show_straggler)


def destroy_process_group():
    global cdb
    cdb = None
    set_collectives_engine(None)
    # Drop jitted-collective caches so stale Mesh objects and their XLA
    # executables can be garbage collected.
    from . import backend as _backend
    for fn in (_backend._jit_all_reduce, _backend._jit_all_gather,
               _backend._jit_reduce_scatter, _backend._jit_broadcast,
               _backend._jit_all_to_all):
        fn.cache_clear()
    from .collectives import clear_jit_caches
    clear_jit_caches()
