"""Multinode launch backends — reference ``launcher/multinode_runner.py``
(``PDSHRunner`` :51, ``OpenMPIRunner`` :118, ``SlurmRunner`` :336).

Each runner turns (args, world_info, env) into the shell command that starts
``launcher.launch`` on every node.  The SSHRunner is the zero-dependency
fallback (plain ssh fan-out, reference uses pdsh for this role).
"""

import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import quote


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.user_arguments = list(args.user_args)
        self.user_script = args.user_script
        self.exports = {}

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        ...

    def add_export(self, key, var):
        self.exports[key.strip()] = str(var).strip()

    @property
    def name(self):
        return self.__class__.__name__.replace("Runner", "").lower()

    def _launch_cmd(self, node_rank_expr):
        cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={self.world_info_base64}",
               f"--node_rank={node_rank_expr}",
               f"--master_addr={self.args.master_addr}",
               f"--master_port={self.args.master_port}"]
        if self.args.no_python:
            cmd.append("--no_python")
        if self.args.module:
            cmd.append("--module")
        if getattr(self.args, "elastic_training", False):
            cmd.append("--enable_elastic_training")
        cmd.append(self.user_script)
        cmd.extend(self.user_arguments)
        return cmd


class PDSHRunner(MultiNodeRunner):
    """Reference ``multinode_runner.py:51``."""

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment = dict(environment)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        exports = "".join(f"export {quote(k)}={quote(v)}; "
                          for k, v in {**environment, **self.exports}.items())
        # %n expands to the pdsh node-index on each host
        launch = " ".join(
            map(quote, self._launch_cmd("%n")))
        return ["pdsh", "-S", "-f", "1024", "-w", active_workers] + \
            (self.args.launcher_args.split() if self.args.launcher_args
             else []) + [exports + launch]


class SSHRunner(MultiNodeRunner):
    """Plain-ssh fan-out (sequential spawn, parallel run)."""

    def backend_exists(self):
        return shutil.which("ssh") is not None

    def get_cmd(self, environment, active_resources):
        # emitted as a shell script: one ssh per node, backgrounded; collect
        # each pid and propagate the worst exit code (bare `wait` is always 0)
        lines = ["pids=()", "rc=0"]
        exports = "".join(f"export {quote(k)}={quote(v)}; "
                          for k, v in {**environment, **self.exports}.items())
        for rank, host in enumerate(active_resources):
            launch = " ".join(map(quote, self._launch_cmd(str(rank))))
            lines.append(f"ssh -o StrictHostKeyChecking=no {quote(host)} "
                         f"{quote(exports + launch)} &")
            lines.append("pids+=($!)")
        lines.append('for p in "${pids[@]}"; do wait "$p" || rc=$?; done')
        lines.append("exit $rc")
        return ["bash", "-c", "\n".join(lines)]


class OpenMPIRunner(MultiNodeRunner):
    """Reference ``multinode_runner.py:118`` — mpirun with one slot per node
    (the node-local spawner handles devices)."""

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total_nodes = len(active_resources)
        hosts = ",".join(f"{h}:1" for h in active_resources)
        cmd = ["mpirun", "-n", str(total_nodes), "--host", hosts,
               "--mca", "btl", "^openib", "--mca", "btl_tcp_if_include",
               "eth0"]
        for k, v in {**environment, **self.exports}.items():
            cmd += ["-x", f"{k}={v}"]
        if self.args.launcher_args:
            cmd += self.args.launcher_args.split()
        # under MPI each rank IS the node process: OMPI_COMM_WORLD_RANK
        # provides node_rank via env in launch.py (no --node_rank flag)
        launch = self._launch_cmd("0")
        launch.remove("--node_rank=0")
        cmd += launch
        return cmd


class SlurmRunner(MultiNodeRunner):
    """Reference ``multinode_runner.py:336`` — srun."""

    def backend_exists(self):
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        total_nodes = len(active_resources)
        cmd = ["srun", "-N", str(total_nodes), "--ntasks-per-node=1",
               "--nodelist", ",".join(active_resources.keys())]
        if self.args.launcher_args:
            cmd += self.args.launcher_args.split()
        exports = ",".join(f"{k}={v}" for k, v in
                           {**environment, **self.exports}.items())
        if exports:
            cmd += [f"--export=ALL,{exports}"]
        # SLURM_PROCID supplies node_rank via env in launch.py
        launch = self._launch_cmd("0")
        launch.remove("--node_rank=0")
        cmd += launch
        return cmd
