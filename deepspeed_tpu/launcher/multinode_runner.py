"""Multinode launch backends — reference ``launcher/multinode_runner.py``
(``PDSHRunner`` :51, ``OpenMPIRunner`` :118, ``SlurmRunner`` :336).

Each runner turns (args, world_info, env) into the shell command that starts
``launcher.launch`` on every node.  The SSHRunner is the zero-dependency
fallback (plain ssh fan-out, reference uses pdsh for this role).
"""

import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import quote


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.user_arguments = list(args.user_args)
        self.user_script = args.user_script
        self.exports = {}

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        ...

    def add_export(self, key, var):
        self.exports[key.strip()] = str(var).strip()

    @property
    def name(self):
        return self.__class__.__name__.replace("Runner", "").lower()

    def _launch_cmd(self, node_rank_expr):
        cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={self.world_info_base64}",
               f"--node_rank={node_rank_expr}",
               f"--master_addr={self.args.master_addr}",
               f"--master_port={self.args.master_port}"]
        if self.args.no_python:
            cmd.append("--no_python")
        if self.args.module:
            cmd.append("--module")
        if getattr(self.args, "elastic_training", False):
            cmd.append("--enable_elastic_training")
        if getattr(self.args, "one_proc_per_device", False):
            cmd.append("--one_proc_per_device")
        if getattr(self.args, "bind_cores_to_rank", False):
            cmd.append("--bind_cores_to_rank")
            if getattr(self.args, "bind_core_list", None):
                cmd.append(f"--bind_core_list={self.args.bind_core_list}")
        cmd.append(self.user_script)
        cmd.extend(self.user_arguments)
        return cmd


class PDSHRunner(MultiNodeRunner):
    """Reference ``multinode_runner.py:51``."""

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment = dict(environment)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        exports = "".join(f"export {quote(k)}={quote(v)}; "
                          for k, v in {**environment, **self.exports}.items())
        # %n expands to the pdsh node-index on each host
        launch = " ".join(
            map(quote, self._launch_cmd("%n")))
        return ["pdsh", "-S", "-f", "1024", "-w", active_workers] + \
            (self.args.launcher_args.split() if self.args.launcher_args
             else []) + [exports + launch]


class SSHRunner(MultiNodeRunner):
    """Plain-ssh fan-out (sequential spawn, parallel run)."""

    def backend_exists(self):
        return shutil.which("ssh") is not None

    def get_cmd(self, environment, active_resources):
        # emitted as a shell script: one ssh per node, backgrounded; collect
        # each pid and propagate the worst exit code (bare `wait` is always 0)
        lines = ["pids=()", "rc=0"]
        exports = "".join(f"export {quote(k)}={quote(v)}; "
                          for k, v in {**environment, **self.exports}.items())
        for rank, host in enumerate(active_resources):
            launch = " ".join(map(quote, self._launch_cmd(str(rank))))
            lines.append(f"ssh -o StrictHostKeyChecking=no {quote(host)} "
                         f"{quote(exports + launch)} &")
            lines.append("pids+=($!)")
        lines.append('for p in "${pids[@]}"; do wait "$p" || rc=$?; done')
        lines.append("exit $rc")
        return ["bash", "-c", "\n".join(lines)]


class OpenMPIRunner(MultiNodeRunner):
    """Reference ``multinode_runner.py:118`` — mpirun with one slot per node
    (the node-local spawner handles devices)."""

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total_nodes = len(active_resources)
        hosts = ",".join(f"{h}:1" for h in active_resources)
        cmd = ["mpirun", "-n", str(total_nodes), "--host", hosts,
               "--mca", "btl", "^openib", "--mca", "btl_tcp_if_include",
               "eth0"]
        for k, v in {**environment, **self.exports}.items():
            cmd += ["-x", f"{k}={v}"]
        if self.args.launcher_args:
            cmd += self.args.launcher_args.split()
        # under MPI each rank IS the node process: OMPI_COMM_WORLD_RANK
        # provides node_rank via env in launch.py (no --node_rank flag)
        launch = self._launch_cmd("0")
        launch.remove("--node_rank=0")
        cmd += launch
        return cmd


class MPICHRunner(MultiNodeRunner):
    """Reference ``multinode_runner.py:179`` — Hydra-style mpirun
    (``-ppn`` / ``-genv`` / ``-hosts``).  One launcher process per node;
    ``launch.py`` spawns the node-local workers (PMI_RANK → node_rank)."""

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        cmd = ["mpirun", "-n", str(len(active_resources)), "-ppn", "1",
               "-hosts", ",".join(active_resources)]
        if self.args.launcher_args:
            cmd += self.args.launcher_args.split()
        for k, v in {**environment, **self.exports}.items():
            cmd += ["-genv", f"{k}={v}"]
        launch = self._launch_cmd("0")
        launch.remove("--node_rank=0")   # PMI_RANK supplies it per node
        return cmd + launch


class IMPIRunner(MPICHRunner):
    """Reference ``multinode_runner.py:251`` — Intel MPI: the same Hydra
    front-end with an explicit ssh bootstrap."""

    def get_cmd(self, environment, active_resources):
        cmd = super().get_cmd(environment, active_resources)
        # insert after "mpirun": bootstrap selection is an Intel-ism
        return cmd[:1] + ["-bootstrap", "ssh"] + cmd[1:]


class MVAPICHRunner(MultiNodeRunner):
    """Reference ``multinode_runner.py:384`` — ``mpirun_rsh`` with a written
    hostfile and k=v environment args; the reference's MV2_* tuning exports
    are applied minus the CUDA-only ones (N/A on TPU)."""

    def __init__(self, args, world_info_base64):
        super().__init__(args, world_info_base64)
        self.add_export("MV2_SMP_USE_CMA", "0")        # CMA absent on Ubuntu
        self.add_export("MV2_DEBUG_SHOW_BACKTRACE", "1")
        self.add_export("MV2_SUPPORT_DL", "1")
        self.add_export("MV2_ENABLE_AFFINITY", "0")    # MPI_THREAD_MULTIPLE

    def backend_exists(self):
        if shutil.which("mpirun_rsh") is None:
            return False
        mpiname = shutil.which("mpiname")
        if mpiname is None:
            return False
        try:
            import subprocess
            out = subprocess.check_output([mpiname]).decode()
            return "MVAPICH" in out
        except (OSError, subprocess.CalledProcessError):
            return False

    def get_cmd(self, environment, active_resources):
        hostfile = os.path.join(os.path.expanduser("~"),
                                ".deepspeed_mvapich_hostfile")
        with open(hostfile, "w") as f:
            f.write("\n".join(active_resources) + "\n")
        cmd = ["mpirun_rsh", "-np", str(len(active_resources)),
               "-hostfile", hostfile]
        if self.args.launcher_args:
            cmd += self.args.launcher_args.split()
        for k, v in {**environment, **self.exports}.items():
            cmd += [f"{k}={v}"]     # mpirun_rsh takes env as k=v positionals
        launch = self._launch_cmd("0")
        launch.remove("--node_rank=0")   # MV2_COMM_WORLD_RANK / PMI_RANK
        return cmd + launch


class SlurmRunner(MultiNodeRunner):
    """Reference ``multinode_runner.py:336`` — srun."""

    def backend_exists(self):
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        total_nodes = len(active_resources)
        cmd = ["srun", "-N", str(total_nodes), "--ntasks-per-node=1",
               "--nodelist", ",".join(active_resources.keys())]
        if self.args.launcher_args:
            cmd += self.args.launcher_args.split()
        exports = ",".join(f"{k}={v}" for k, v in
                           {**environment, **self.exports}.items())
        if exports:
            cmd += [f"--export=ALL,{exports}"]
        # SLURM_PROCID supplies node_rank via env in launch.py
        launch = self._launch_cmd("0")
        launch.remove("--node_rank=0")
        cmd += launch
        return cmd
