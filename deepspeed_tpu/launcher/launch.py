"""Per-node process spawner — reference ``launcher/launch.py:133 main``.

Reference behavior: decode base64 world-info, set CUDA_VISIBLE_DEVICES-like
env via the accelerator (:166), export RANK/LOCAL_RANK/MASTER_*, fork one
subprocess per local device, fan out signals, write pid files.

TPU-native: JAX wants **one process per host** that owns every local chip
(SPMD), so the default is a single child per node with
``JAX_PROCESS_COUNT = num_nodes`` and ``COORDINATOR_ADDRESS`` rendezvous.
``--one_proc_per_device`` restores the reference's process-per-device layout
(sets ``TPU_VISIBLE_DEVICES``/``TPU_PROCESS_BOUNDS`` per child) for tools
that need it.  Both MASTER_* and COORDINATOR_ADDRESS spellings are exported.
"""

import argparse
import os
import signal
import subprocess
import sys
import time

from ..utils.logging import logger
from .runner import decode_world_info

PID_FILE_BASEPATH = "/tmp"


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", type=str, required=True)
    parser.add_argument("--node_rank", type=int,
                        default=int(os.environ.get(
                            "NODE_RANK",
                            os.environ.get(
                                "OMPI_COMM_WORLD_RANK",
                                os.environ.get(
                                    "SLURM_PROCID",
                                    # MPICH/IMPI Hydra + MVAPICH mpirun_rsh
                                    os.environ.get(
                                        "PMI_RANK",
                                        os.environ.get(
                                            "MV2_COMM_WORLD_RANK", 0)))))))
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--one_proc_per_device", action="store_true")
    parser.add_argument("--bind_cores_to_rank", action="store_true",
                        help="numactl-bind each local process to its core "
                        "slice (reference utils/numa.py get_numactl_cmd).")
    parser.add_argument("--bind_core_list", type=str, default=None,
                        help="Restrict binding to these cores, e.g. "
                        "'0-27,32-59'.")
    parser.add_argument("--no_python", action="store_true")
    parser.add_argument("--module", action="store_true")
    parser.add_argument("--enable_elastic_training", action="store_true")
    parser.add_argument("--min_elastic_nodes", type=int, default=-1)
    parser.add_argument("--max_elastic_nodes", type=int, default=-1)
    parser.add_argument("--stall_timeout", type=float, default=0.0,
                        help="Elastic watchdog: kill+relaunch a worker "
                        "whose newest heartbeat is older than this many "
                        "seconds (0 disables hang detection; set well "
                        "above first-step compile time).")
    parser.add_argument("--heartbeat_dir", type=str, default=None,
                        help="Directory for worker heartbeat files "
                        "(exported to workers as DS_TPU_HEARTBEAT_DIR; "
                        "default: a per-agent tempdir).")
    parser.add_argument("--restart_backoff", type=float, default=1.0,
                        help="Base seconds of exponential backoff between "
                        "elastic restarts (doubles per restart, capped).")
    parser.add_argument("--save_pid", action="store_true")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def build_child_env(args, world_info, node_rank, local_rank, procs_per_node):
    """Environment for one child process."""
    hosts = list(world_info.keys())
    num_nodes = len(hosts)
    env = os.environ.copy()
    coordinator = f"{args.master_addr}:{args.master_port}"

    if procs_per_node == 1:
        # JAX SPMD: process == host
        world_size = num_nodes
        rank = node_rank
        env["JAX_PROCESS_COUNT"] = str(world_size)
        env["JAX_PROCESS_ID"] = str(rank)
    else:
        world_size = sum(len(s) for s in world_info.values())
        rank = sum(
            len(world_info[h]) for h in hosts[:node_rank]) + local_rank
        env["JAX_PROCESS_COUNT"] = str(world_size)
        env["JAX_PROCESS_ID"] = str(rank)
        slots = world_info[hosts[node_rank]]
        env["TPU_VISIBLE_DEVICES"] = str(slots[local_rank])
        env["CUDA_VISIBLE_DEVICES"] = str(slots[local_rank])

    if world_size > 1:
        env["COORDINATOR_ADDRESS"] = coordinator
    # torch-style spellings for user scripts that read them
    env["MASTER_ADDR"] = args.master_addr
    env["MASTER_PORT"] = str(args.master_port)
    env["WORLD_SIZE"] = str(world_size)
    env["RANK"] = str(rank)
    env["LOCAL_RANK"] = str(local_rank)
    env["CROSS_RANK"] = str(node_rank)
    env["CROSS_SIZE"] = str(num_nodes)
    env["LOCAL_SIZE"] = str(procs_per_node)
    return env


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    hosts = list(world_info.keys())
    node_rank = args.node_rank
    assert 0 <= node_rank < len(hosts), \
        f"node_rank {node_rank} out of range for {len(hosts)} hosts"
    procs_per_node = (len(world_info[hosts[node_rank]])
                      if args.one_proc_per_device else 1)

    def child_cmd():
        cmd = []
        if not args.no_python:
            cmd = [sys.executable, "-u"]
            if args.module:
                cmd.append("-m")
        cmd.append(args.training_script)
        cmd.extend(args.training_script_args)
        return cmd

    if args.enable_elastic_training:
        # restart supervision (reference DSElasticAgent via torchelastic,
        # elasticity/elastic_agent.py:32): relaunch failed workers; state
        # recovery = checkpoint+resume in the training script
        from ..elasticity.elastic_agent import DSElasticAgent
        if procs_per_node != 1:
            logger.warning(
                "elastic training supervises one worker per node; "
                "--one_proc_per_device (%d local devices) is ignored — the "
                "worker owns all local chips via jax.local_devices()",
                procs_per_node)
        env = build_child_env(args, world_info, node_rank, 0, 1)
        agent = DSElasticAgent(child_cmd(), env, ds_config=None,
                               min_nodes=args.min_elastic_nodes,
                               max_nodes=args.max_elastic_nodes,
                               heartbeat_dir=args.heartbeat_dir,
                               stall_timeout=args.stall_timeout,
                               restart_backoff=args.restart_backoff)
        sys.exit(agent.run(world_size=len(hosts)))

    processes = []
    for local_rank in range(procs_per_node):
        env = build_child_env(args, world_info, node_rank, local_rank,
                              procs_per_node)
        cmd = child_cmd()
        if args.bind_cores_to_rank:
            # keep the host-optimizer/aio threads NUMA-local per process
            from ..utils.numa import get_numactl_cmd
            prefix, per_rank = get_numactl_cmd(args.bind_core_list,
                                               procs_per_node, local_rank)
            env.setdefault("OMP_NUM_THREADS", str(per_rank))
            cmd = prefix + cmd
        logger.info("launching rank %s: %s", env["RANK"], " ".join(cmd))
        processes.append(subprocess.Popen(cmd, env=env))

    if args.save_pid:
        pid_path = os.path.join(PID_FILE_BASEPATH,
                                f"ds_launch_{os.getpid()}.pids")
        with open(pid_path, "w") as f:
            f.write(",".join(str(p.pid) for p in processes))

    def sigkill_handler(signum, frame):
        # reference launch.py:317 — fan the signal out and die
        for p in processes:
            if p.poll() is None:
                p.send_signal(signum)
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, sigkill_handler)
    signal.signal(signal.SIGTERM, sigkill_handler)

    # monitor: if any child fails, kill the rest (reference sigkill_handler)
    alive = list(processes)
    rc = 0
    while alive:
        for p in list(alive):
            ret = p.poll()
            if ret is None:
                continue
            alive.remove(p)
            if ret != 0:
                rc = ret
                logger.error("child %s exited with %s — terminating node",
                             p.pid, ret)
                for q in alive:
                    if q.poll() is None:
                        q.terminate()
                alive = []
                break
        time.sleep(0.5)
    sys.exit(rc)


if __name__ == "__main__":
    main()
