"""ds_ssh — run a command on every host of a hostfile (reference
``bin/ds_ssh``; that one shells out to pdsh, this one runs plain ``ssh``
per host in a thread pool so there is no pdsh dependency on TPU pods).

    ds_ssh [-f hostfile] [--serial] [--timeout S] -- <command...>

Output is prefixed per host (pdsh-style ``host: line``); exit status is
non-zero if any host fails.  Hostfile format is the launcher's
(``host slots=N``, comments with '#') — ``fetch_hostfile`` is shared.
"""

import argparse
import shlex
import subprocess
import sys
from concurrent.futures import (ThreadPoolExecutor,
                                as_completed)

from .runner import DLTS_HOSTFILE, fetch_hostfile

SSH_OPTS = ["-o", "StrictHostKeyChecking=no", "-o", "BatchMode=yes"]


def _run_one(host, command, timeout):
    try:
        proc = subprocess.run(["ssh"] + SSH_OPTS + [host, command],
                              capture_output=True, text=True,
                              timeout=timeout)
        return host, proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired:
        return host, 124, "", f"timeout after {timeout}s\n"
    except OSError as e:  # ssh binary missing etc.
        return host, 127, "", f"{e}\n"


def _emit(host, rc, out, err):
    for line in out.splitlines():
        print(f"{host}: {line}")
    for line in err.splitlines():
        print(f"{host}: {line}", file=sys.stderr)
    if rc != 0:
        print(f"{host}: [exit {rc}]", file=sys.stderr)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_ssh", description="run a command on every hostfile host")
    parser.add_argument("-f", "--hostfile", default=DLTS_HOSTFILE,
                        help=f"hostfile path (default {DLTS_HOSTFILE})")
    parser.add_argument("--serial", action="store_true",
                        help="one host at a time (default: parallel)")
    parser.add_argument("--timeout", type=float, default=None,
                        help="per-host timeout in seconds")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run (prefix with -- if it has flags)")
    args = parser.parse_args(argv)
    cmd = list(args.command)
    if cmd and cmd[0] == "--":  # strip only the leading separator
        cmd = cmd[1:]
    if not cmd:
        parser.error("no command given")
    command = shlex.join(cmd)

    resources = fetch_hostfile(args.hostfile)
    if not resources:
        print(f"Missing/empty hostfile at {args.hostfile}, unable to proceed",
              file=sys.stderr)
        return 1
    hosts = list(resources.keys())

    failed = 0
    if args.serial:
        for h in hosts:
            res = _run_one(h, command, args.timeout)
            _emit(*res)
            failed += res[1] != 0
    else:
        # stream each host's result as it finishes (pdsh behavior) — one
        # hung host must not withhold the finished hosts' output
        with ThreadPoolExecutor(max_workers=min(64, len(hosts))) as pool:
            futs = [pool.submit(_run_one, h, command, args.timeout)
                    for h in hosts]
            for fut in as_completed(futs):
                res = fut.result()
                _emit(*res)
                failed += res[1] != 0
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
