"""``deepspeed`` CLI — multi-node launcher front-end.

Reference: ``launcher/runner.py`` (arg parsing :48, hostfile :213,
include/exclude filters :293, world-info encode :384, ``main`` :419 picks a
multinode backend and ``exec``s it).

TPU-native redesign: the unit of launch is a **host process driving all local
chips** (JAX SPMD convention), not one process per device.  Rendezvous is
``COORDINATOR_ADDRESS`` (``jax.distributed.initialize``) rather than
MASTER_ADDR/MASTER_PORT NCCL rendezvous — the launcher sets both spellings so
user scripts written against either work.  Single-node launches skip ssh and
exec ``launch.py`` directly.
"""

import argparse
import base64
import json
import os
import shlex
import subprocess
import sys
from collections import OrderedDict

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ("PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "JAX_PLATFORMS",
               "XLA_FLAGS", "LIBTPU_INIT_ARGS", "TPU_NAME", "DS_ACCELERATOR")


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed-tpu distributed launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile path: lines of '<host> slots=<n>'.")
    parser.add_argument("-i", "--include", type=str, default="",
                        help='Include filter, e.g. "worker-0@worker-1:0,2".')
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help='Exclude filter, e.g. "worker-1:0".')
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Limit to first N hosts of the resource pool.")
    parser.add_argument("--num_gpus", "--num_chips", dest="num_gpus",
                        type=int, default=-1,
                        help="Limit devices per node.")
    parser.add_argument("--master_port", type=int,
                        default=int(os.environ.get("DS_MASTER_PORT", 29500)),
                        help="Coordinator port.")
    parser.add_argument("--master_addr", type=str,
                        default=os.environ.get("DS_MASTER_ADDR", ""),
                        help="Coordinator address (default: first host).")
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=("pdsh", "openmpi", "mpich", "impi",
                                 "mvapich", "slurm", "ssh", "local"),
                        help="Multinode backend.")
    parser.add_argument("--launcher_args", type=str, default="",
                        help="Extra args passed to the multinode backend.")
    parser.add_argument("--force_multi", action="store_true",
                        help="Treat as multi-node even for one host.")
    parser.add_argument("--autotuning", type=str, default="",
                        choices=("", "tune", "run"),
                        help="Run the autotuner to discover config.")
    parser.add_argument("--elastic_training", action="store_true",
                        help="Enable elastic batch/worker scheduling.")
    parser.add_argument("--one_proc_per_device", action="store_true",
                        help="Reference process-per-device layout instead "
                        "of the JAX one-process-per-host default "
                        "(forwarded to launch.py).")
    parser.add_argument("--no_python", action="store_true",
                        help="Run user_script directly (not via python).")
    parser.add_argument("--module", action="store_true",
                        help="Run user_script as a python module (-m).")
    parser.add_argument("--venv_script", type=str, default=None,
                        help="Activation script sourced before launch.")
    parser.add_argument("--bind_cores_to_rank", action="store_true",
                        help="numactl-bind each local process.")
    parser.add_argument("--bind_core_list", type=str, default=None,
                        help="Restrict binding to these cores, e.g. "
                        "'0-27,32-59'.")
    parser.add_argument("user_script", type=str,
                        help="User training script.")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """'<host> slots=<n>' lines → OrderedDict host→slots (reference :213)."""
    if not os.path.isfile(hostfile_path):
        return None
    resource_pool = OrderedDict()
    with open(hostfile_path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(f"Hostfile is not formatted correctly, "
                                 f"unable to parse line: {line!r}")
            if hostname in resource_pool:
                raise ValueError(f"Hostfile contains duplicate hosts: "
                                 f"{hostname}")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_hostlist_entry(entry):
    if ":" in entry:
        host, slots = entry.split(":")
        return host, [int(x) for x in slots.split(",")]
    return entry, None


def parse_resource_filter(host_info, include_str="", exclude_str=""):
    """Apply '@'-separated host[:slot,slot] filters (reference :293)."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive")
    filtered = OrderedDict()
    if include_str:
        for entry in include_str.split("@"):
            host, slots = _parse_hostlist_entry(entry.strip())
            if host not in host_info:
                raise ValueError(f"Hostname '{host}' not found in hostfile")
            if slots is None:
                filtered[host] = host_info[host]
            else:
                for s in slots:
                    if s not in host_info[host]:
                        raise ValueError(
                            f"No slot '{s}' specified on host '{host}'")
                filtered[host] = sorted(slots)
        return filtered
    # exclude path: start from everything
    for host, slots in host_info.items():
        filtered[host] = slots
    if exclude_str:
        for entry in exclude_str.split("@"):
            host, slots = _parse_hostlist_entry(entry.strip())
            if host not in filtered:
                raise ValueError(f"Hostname '{host}' not found in hostfile")
            if slots is None:
                del filtered[host]
            else:
                remaining = [
                    s for s in host_info[host] if s not in slots
                ]
                if remaining:
                    filtered[host] = remaining
                else:
                    del filtered[host]
    return filtered


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    active_resources = OrderedDict()
    for hostname, slots in resource_pool.items():
        active_resources[hostname] = list(range(slots))
    return parse_resource_filter(active_resources, include_str=inclusion,
                                 exclude_str=exclusion)


def encode_world_info(world_info):
    """dict host→[slots] → base64 json (reference :384)."""
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def decode_world_info(encoded):
    return json.loads(base64.urlsafe_b64decode(encoded).decode())


def _local_device_count():
    try:
        from ..accelerator import get_accelerator
        return max(get_accelerator().device_count(), 1)
    except Exception:
        return 1


def build_launch_command(args, active_resources):
    """Construct the per-node ``launch.py`` command (single-node path) or the
    multinode runner command."""
    from .multinode_runner import (IMPIRunner, MPICHRunner, MVAPICHRunner,
                                   OpenMPIRunner, PDSHRunner, SlurmRunner,
                                   SSHRunner)
    world_info = encode_world_info(active_resources)
    multi_node = args.force_multi or len(active_resources) > 1
    if not multi_node:
        cmd = [
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={world_info}",
            f"--master_addr={args.master_addr or 'localhost'}",
            f"--master_port={args.master_port}",
        ]
        if args.one_proc_per_device:
            cmd.append("--one_proc_per_device")
        if args.bind_cores_to_rank:
            cmd.append("--bind_cores_to_rank")
            if args.bind_core_list:
                cmd.append(f"--bind_core_list={args.bind_core_list}")
        if args.no_python:
            cmd.append("--no_python")
        if args.module:
            cmd.append("--module")
        if args.elastic_training:
            cmd.append("--enable_elastic_training")
        cmd.append(args.user_script)
        cmd.extend(args.user_args)
        return cmd

    runner_cls = {"pdsh": PDSHRunner, "openmpi": OpenMPIRunner,
                  "mpich": MPICHRunner, "impi": IMPIRunner,
                  "mvapich": MVAPICHRunner,
                  "slurm": SlurmRunner, "ssh": SSHRunner}[args.launcher]
    runner = runner_cls(args, world_info)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {args.launcher} not installed")
    env = {k: os.environ[k] for k in EXPORT_ENVS if k in os.environ}
    return runner.get_cmd(env, active_resources)


def main(args=None):
    args = parse_args(args)

    if args.bind_core_list and not args.bind_cores_to_rank:
        logger.warning("--bind_core_list has no effect without "
                       "--bind_cores_to_rank; processes run unbound")

    if args.autotuning:
        from ..autotuning.autotuner import run_autotuning
        return run_autotuning(args)

    resource_pool = fetch_hostfile(args.hostfile)
    if resource_pool is None:
        n = args.num_gpus if args.num_gpus > 0 else _local_device_count()
        resource_pool = OrderedDict(localhost=n)
    active_resources = parse_inclusion_exclusion(resource_pool, args.include,
                                                 args.exclude)
    if args.num_nodes > 0:
        active_resources = OrderedDict(
            list(active_resources.items())[:args.num_nodes])
    if args.num_gpus > 0:
        active_resources = OrderedDict(
            (h, s[:args.num_gpus]) for h, s in active_resources.items())
    if not args.master_addr:
        args.master_addr = next(iter(active_resources))
        if args.master_addr == "localhost":
            args.master_addr = "127.0.0.1"

    cmd = build_launch_command(args, active_resources)
    logger.info("cmd = %s", " ".join(map(shlex.quote, cmd)))
    result = subprocess.Popen(cmd)
    result.wait()
    sys.exit(result.returncode)


if __name__ == "__main__":
    main()
