"""Compression scheduler (reference ``compression/scheduler.py``): each method
activates at its ``schedule_offset`` (and optionally deactivates at
``schedule_offset_end``); the engine calls ``step()`` once per optimizer
step."""


class CompressionScheduler:

    def __init__(self, manager):
        self.manager = manager
        self.training_steps = 0

    def step(self, step_zero_check=False):
        self.training_steps += 1
        self.manager.on_step(self.training_steps)
