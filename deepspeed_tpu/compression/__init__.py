"""Compression subsystem (reference ``deepspeed/compression/``): QAT weight/
activation quantization, sparse/row/head/channel pruning, layer reduction —
config-driven, same JSON schema."""

from .compress import (init_compression, redundancy_clean,
                       student_initialization)
from .quantizers import fake_quantize, quant_act
from .scheduler import CompressionScheduler
