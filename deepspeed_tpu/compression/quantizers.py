"""Quantization-aware-training fake-quantizers (reference
``compression/basic_layer.py`` quantize functions + ``utils.py``).

All quantizers are straight-through (identity backward) so QAT gradients flow
— the reference achieves this with autograd Functions; here a custom_vjp.
Per-group quantization reshapes to (groups, -1) like the reference's
``quantize_groups``.
"""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def fake_quantize(x, bits, symmetric=True, num_groups=1):
    """Quantize-dequantize ``x`` to ``bits`` with a straight-through grad."""
    return _fq_impl(x, bits, symmetric, num_groups)


def _fq_impl(x, bits, symmetric, num_groups):
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    g = max(1, min(num_groups, n))
    pad = (-n) % g
    # edge-pad: zero padding would pollute the last group's min/max range
    flat = jnp.pad(flat, (0, pad), mode="edge")
    grp = flat.reshape(g, -1)
    if symmetric:
        qmax = 2.0**(bits - 1) - 1
        scale = jnp.maximum(jnp.abs(grp).max(axis=1, keepdims=True), 1e-8) / qmax
        q = jnp.clip(jnp.round(grp / scale), -qmax, qmax)
        out = q * scale
    else:
        levels = 2.0**bits - 1
        lo = grp.min(axis=1, keepdims=True)
        hi = grp.max(axis=1, keepdims=True)
        scale = jnp.maximum(hi - lo, 1e-8) / levels
        q = jnp.clip(jnp.round((grp - lo) / scale), 0, levels)
        out = q * scale + lo
    return out.reshape(-1)[:n].reshape(orig_shape).astype(orig_dtype)


def _fq_fwd(x, bits, symmetric, num_groups):
    return _fq_impl(x, bits, symmetric, num_groups), None


def _fq_bwd(bits, symmetric, num_groups, _, g):
    return (g, )


fake_quantize.defvjp(_fq_fwd, _fq_bwd)


def quant_act(x, bits, symmetric=False):
    """Activation fake-quant with dynamic per-tensor range (reference
    ``QuantAct`` with range_calibration="dynamic", basic_layer.py:17) — model
    code calls this at the annotated activation sites."""
    return fake_quantize(x, bits, symmetric, 1)


def bits_schedule(step, start_bits, target_bits, offset, period):
    """Staged bit reduction (reference weight-quant schedule: bits step down
    every ``quantization_period`` steps after ``schedule_offset``):
    start → midpoint → target.  Returns None while quantization is off."""
    if step < offset:
        return None
    if period <= 0 or start_bits <= target_bits:
        return target_bits
    drops = (step - offset) // period
    ladder = [start_bits, (start_bits + target_bits) // 2, target_bits]
    return ladder[min(drops, 2)] if drops < len(ladder) else target_bits
