"""Pruning-mask builders (reference ``compression/basic_layer.py``
LinearLayer_Compress mask logic: sparse/row/head/channel, l1 | topk).

Masks are computed from weight magnitudes on the host side of the step
boundary and re-applied after every optimizer step (functionally identical to
the reference's masked-forward: the optimizer may move a pruned weight, the
mask zeroes it again before it is ever used).

Convention: 2D kernels are [in_features, out_features] (flax DenseGeneral);
"row" pruning removes *output* features (reference prunes nn.Linear rows =
output neurons) → masks along the LAST dim; the related-module mask (the
consumer's input dim) applies along the FIRST dim.
"""

import numpy as np

import jax.numpy as jnp


def _keep_k(scores, ratio):
    k = max(1, int(round(scores.size * ratio)))
    thresh = np.partition(scores.reshape(-1), -k)[-k]
    return scores >= thresh


def sparse_mask(w, dense_ratio, method="l1", block_pattern=None):
    """Unstructured (or block-structured) magnitude mask."""
    w = np.asarray(w, np.float32)
    if method not in ("l1", "topk", "snip_momentum"):
        raise ValueError(f"unknown sparse pruning method {method!r}")
    scores = np.abs(w)
    if block_pattern and block_pattern != "1x1" and w.ndim >= 2:
        # "RxC" blocks over the trailing 2 dims score by block l1 mean
        r, c = (int(t) for t in block_pattern.split("x"))
        rows, cols = w.shape[-2], w.shape[-1]
        r, c = min(r, rows), min(c, cols)
        rr, cc = rows - rows % r, cols - cols % c
        lead = w.shape[:-2]
        blk = scores[..., :rr, :cc].reshape(*lead, rr // r, r, cc // c, c)
        blk_score = blk.mean(axis=(-3, -1))
        keep = _keep_k(blk_score, dense_ratio)
        mask = np.zeros_like(scores, dtype=bool)
        mask[..., :rr, :cc] = np.repeat(np.repeat(keep, r, axis=-2), c,
                                        axis=-1)
        mask[..., rr:, :] = True
        mask[..., :, cc:] = True
        return jnp.asarray(mask, jnp.float32)
    return jnp.asarray(_keep_k(scores, dense_ratio), jnp.float32)


def row_mask(w, dense_ratio, method="l1"):
    """Output-feature mask [out] from a [in, out] kernel."""
    w = np.asarray(w, np.float32)
    scores = np.abs(w).sum(axis=tuple(range(w.ndim - 1)))
    return jnp.asarray(_keep_k(scores, dense_ratio), jnp.float32)


def head_mask(w, dense_ratio, num_heads, method="topk"):
    """Head mask for an attention output projection [in(=H*dh), out]: score
    heads by the l1 norm of their input slice (reference head_pruning on
    attention.output.dense with related qkv)."""
    w = np.asarray(w, np.float32)
    in_dim = w.shape[0]
    if in_dim % num_heads:
        raise ValueError(f"in dim {in_dim} not divisible by {num_heads} heads")
    per = in_dim // num_heads
    scores = np.abs(w).reshape(num_heads, per, -1).sum(axis=(1, 2))
    keep = _keep_k(scores, dense_ratio)
    return jnp.asarray(np.repeat(keep, per), jnp.float32)  # [in]


def channel_mask(w, dense_ratio, method="l1"):
    """Input-feature (channel) mask [in] from a [in, out] kernel."""
    w = np.asarray(w, np.float32)
    scores = np.abs(w).reshape(w.shape[0], -1).sum(axis=1)
    return jnp.asarray(_keep_k(scores, dense_ratio), jnp.float32)


def apply_dim_mask(w, mask, axis):
    shape = [1] * w.ndim
    shape[axis] = mask.shape[0]
    return w * mask.reshape(shape).astype(w.dtype)
