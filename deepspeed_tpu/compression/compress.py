"""Compression orchestration (reference ``compression/compress.py``:
``init_compression`` :100, ``redundancy_clean`` :148,
``student_initialization`` :192).

Where the reference swaps ``nn.Linear`` → ``LinearLayer_Compress`` modules,
the TPU engine is functional: compression attaches

  * a differentiable **param transform** (STE fake-quant) composed into the
    engine's apply_fn — QAT inside the jitted micro-step;
  * **masks** re-applied to params (and fp32 master) after every optimizer
    step — pruning that survives optimizer updates;
  * a bit-width **schedule** that invalidates the compiled step when the
    quantization ladder advances.

Module-name patterns are regexes matched against the engine's ``path_str``
parameter paths ('.' in reference-style patterns matches '/' naturally).
"""

import re

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.logging import logger
from . import constants as C
from .pruners import channel_mask, head_mask, row_mask, sparse_mask
from .quantizers import bits_schedule, fake_quantize
from .scheduler import CompressionScheduler


def _flat_params(engine):
    from ..runtime.zero.partition import path_str
    out = {}
    for kp, leaf in jax.tree_util.tree_leaves_with_path(engine.params):
        out[path_str(kp)] = leaf
    return out


def _match(patterns, path):
    return any(re.search(p, path) for p in patterns)


def _apply_mask(w, mask, kind):
    """kind: 'full' (elementwise), 'out' (trailing dims), 'in' (leading)."""
    if kind == "full":
        return w * mask.astype(w.dtype)
    size = mask.shape[0]
    if kind == "out":
        # fold trailing dims until their product == mask size
        prod, k = 1, w.ndim
        while k > 0 and prod < size:
            k -= 1
            prod *= w.shape[k]
        if prod != size:
            return w
        return w * mask.reshape((1, ) * k + w.shape[k:]).astype(w.dtype)
    # 'in'
    prod, k = 1, 0
    while k < w.ndim and prod < size:
        prod *= w.shape[k]
        k += 1
    if prod != size:
        return w
    return w * mask.reshape(w.shape[:k] + (1, ) * (w.ndim - k)).astype(w.dtype)


class _Group:

    def __init__(self, name, params, modules, related=None):
        self.name = name
        self.params = params
        self.modules = modules
        self.related = related or []


def _parse_groups(section):
    shared = section.get(C.SHARED_PARAMETERS, {})
    groups = []
    for name, g in section.get(C.DIFFERENT_GROUPS, {}).items():
        rel = g.get(C.GROUP_RELATED_MODULES) or []
        rel = [p for sub in rel for p in (sub if isinstance(sub, list)
                                          else [sub])]
        groups.append(_Group(name, g.get(C.GROUP_PARAMS, {}),
                             g.get(C.GROUP_MODULES, []), rel))
    return shared, groups


class CompressionManager:
    """Holds all compression state for one engine."""

    def __init__(self, engine, config_dict):
        self.engine = engine
        self.cfg = config_dict.get(C.COMPRESSION_TRAINING, config_dict) or {}
        self.step_count = 0
        self.masks = {}          # path → (mask, kind)
        self._masked_fn = None   # jitted mask application, keyed on mask set
        self.current_bits = {}   # path → int | None
        self._wq_path_groups = None  # lazy path→group cache
        self._wq_shared, self._wq_groups = _parse_groups(
            self.cfg.get(C.WEIGHT_QUANTIZATION, {}))
        self._aq_shared, self._aq_groups = _parse_groups(
            self.cfg.get(C.ACTIVATION_QUANTIZATION, {}))
        self._prune_cfgs = {
            method: _parse_groups(self.cfg.get(method, {}))
            for method in (C.SPARSE_PRUNING, C.ROW_PRUNING, C.HEAD_PRUNING,
                           C.CHANNEL_PRUNING)
        }
        self.scheduler = CompressionScheduler(self)
        self._install()

    # ------------------------------------------------------------ wiring
    def _wq_enabled(self):
        return self._wq_shared.get(C.ENABLED, False) and self._wq_groups

    def _install(self):
        if self._wq_enabled():
            self.engine.register_param_transform(self._quant_transform)
        self.engine.register_post_step_hook(self._post_step)

    def _path_group_map(self):
        """path → wq group, computed once (patterns and the param tree are
        static after install; per-step regexing would be hot-path waste)."""
        if self._wq_path_groups is None:
            self._wq_path_groups = {}
            for path in _flat_params(self.engine).keys():
                for g in self._wq_groups:
                    if _match(g.modules, path):
                        self._wq_path_groups[path] = g
                        break
        return self._wq_path_groups

    def _path_bits(self):
        """path → bits for the current step (None = not yet quantizing)."""
        if not self._wq_enabled():
            return {}
        offset = self._wq_shared.get(C.SCHEDULE_OFFSET, 0)
        return {
            path: bits_schedule(self.step_count,
                                g.params.get(C.START_BITS, 8),
                                g.params.get(C.TARGET_BITS, 8), offset,
                                g.params.get(C.QUANTIZATION_PERIOD, 0))
            for path, g in self._path_group_map().items()
        }

    def _quant_transform(self, params):
        """Differentiable fake-quant over matched leaves (traced — the bits
        dict is static per compile; on_step invalidates when it changes)."""
        bits = dict(self.current_bits)
        if not any(b for b in bits.values()):
            return params
        sym = self._wq_shared.get(C.QUANTIZATION_TYPE,
                                  "symmetric") == "symmetric"
        groups = self._wq_shared.get(C.QUANTIZE_GROUPS, 1)
        from ..runtime.zero.partition import path_str

        def q(kp, x):
            b = bits.get(path_str(kp))
            if not b or x.ndim < 2:
                return x
            return fake_quantize(x, int(b), sym, groups)

        return jax.tree_util.tree_map_with_path(q, params)

    # ------------------------------------------------------------ stepping
    def on_step(self, step):
        self.step_count = step
        if self._wq_enabled():
            new_bits = self._path_bits()
            if new_bits != self.current_bits:
                self.current_bits = new_bits
                self.engine.invalidate_compiled()
        self._update_masks()
        if self.masks:
            self._apply_masks()

    def _update_masks(self):
        if getattr(self, "_masks_final", False):
            return
        before = len(self.masks)
        offsets = [s.get(C.SCHEDULE_OFFSET, 0)
                   for s, _ in self._prune_cfgs.values()
                   if s.get(C.ENABLED, False)]
        flat = _flat_params(self.engine)
        for method, (shared, groups) in self._prune_cfgs.items():
            if not shared.get(C.ENABLED, False):
                continue
            if self.step_count < shared.get(C.SCHEDULE_OFFSET, 0):
                continue
            for g in groups:
                for path, w in flat.items():
                    if w.ndim < 2 or not _match(g.modules, path):
                        continue
                    if path in self.masks:
                        continue  # masks are sticky once computed
                    ratio = g.params.get(C.DENSE_RATIO, 0.5)
                    m = shared.get(C.METHOD, "l1")
                    if method == C.SPARSE_PRUNING:
                        self.masks[path] = (sparse_mask(
                            w, ratio, m,
                            shared.get("block_pattern")), "full")
                    elif method == C.ROW_PRUNING:
                        mask = row_mask(w, ratio, m)
                        self.masks[path] = (mask, "out")
                        for rp, rw in flat.items():
                            if _match(g.related, rp) and rw.ndim >= 2:
                                self.masks[rp] = (mask, "in")
                    elif method == C.HEAD_PRUNING:
                        mask = head_mask(w, ratio,
                                         shared.get(C.NUM_HEADS, 1), m)
                        self.masks[path] = (mask, "in")
                        for rp, rw in flat.items():
                            if _match(g.related, rp) and rw.ndim >= 2:
                                self.masks[rp] = (mask, "out")
                    elif method == C.CHANNEL_PRUNING:
                        self.masks[path] = (channel_mask(w, ratio, m), "in")
        if len(self.masks) != before:
            self._masked_fn = None  # mask set changed → kinds closure stale
        # masks are sticky — once every enabled method is past its offset and
        # a full scan added nothing new, stop re-scanning per step
        if offsets and len(self.masks) == before and \
                self.step_count >= max(offsets):
            self._masks_final = True

    def _apply_masks(self):
        """Multiply the masks into params/master via one jitted (donating)
        program — an eager per-leaf host loop here would serialize the step
        dispatch path every iteration once any mask exists."""
        from ..runtime.zero.partition import path_str

        if self._masked_fn is None:
            kinds = {p: k for p, (_, k) in self.masks.items()}

            def apply_fn(trees, masks):
                def mask_tree(tree):
                    if tree is None:
                        return None

                    def f(kp, x):
                        p = path_str(kp)
                        if p not in masks:
                            return x
                        return _apply_mask(x, masks[p], kinds[p])

                    return jax.tree_util.tree_map_with_path(f, tree)

                return tuple(mask_tree(t) for t in trees)

            self._masked_fn = jax.jit(apply_fn, donate_argnums=0)

        masks = {p: m for p, (m, _) in self.masks.items()}
        self.engine.params, self.engine.master = self._masked_fn(
            (self.engine.params, self.engine.master), masks)

    def _post_step(self, engine):
        self.scheduler.step()

    # ------------------------------------------------------------ reporting
    def sparsity_report(self):
        flat = _flat_params(self.engine)
        rep = {}
        for path, (mask, kind) in self.masks.items():
            m = np.asarray(mask)
            rep[path] = 1.0 - float(m.mean())
        return rep


def init_compression(engine, deepspeed_config=None, teacher_model=None,
                     mpu=None):
    """Attach compression to an engine (reference ``compress.py:100`` — the
    module-rewrite pass becomes transform/mask registration)."""
    cfg = deepspeed_config
    if cfg is None:
        cfg = getattr(engine._config, "_param_dict", {}) or {}
    if isinstance(cfg, str):
        import json
        with open(cfg) as f:
            cfg = json.load(f)
    manager = CompressionManager(engine, cfg)
    engine.compression_manager = manager
    logger.info(f"compression initialized: wq={manager._wq_enabled()} "
                f"methods={[m for m, (s, _) in manager._prune_cfgs.items() if s.get(C.ENABLED)]}")
    return engine


def redundancy_clean(engine, deepspeed_config=None, mpu=None):
    """Bake the masks in (reference ``compress.py:148``): final mask
    application so exported weights carry the pruning pattern."""
    manager = getattr(engine, "compression_manager", None)
    if manager is not None and manager.masks:
        manager._apply_masks()
    return engine


def student_initialization(student_params, teacher_params, deepspeed_config):
    """Layer-reduction init (reference ``compress.py:192``): copy the chosen
    teacher layers into the student (depth-pruned) parameter tree.

    Supports both per-layer subtrees (paths containing ``<prefix>/<idx>/``)
    and stacked-layer leaves (leading dim = num layers) under ``prefix``.
    """
    cfg = deepspeed_config.get(C.COMPRESSION_TRAINING,
                               deepspeed_config).get(C.LAYER_REDUCTION, {})
    if not cfg.get(C.ENABLED, False):
        return student_params
    prefix = cfg.get(C.MODULE_NAME_PREFIX, "")
    teacher_layers = cfg.get(C.TEACHER_LAYER, [])
    from ..runtime.zero.partition import path_str

    t_flat = {}
    for kp, leaf in jax.tree_util.tree_leaves_with_path(teacher_params):
        t_flat[path_str(kp)] = leaf

    def pick(kp, s_leaf):
        path = path_str(kp)
        if prefix and prefix in path:
            tail = path.split(prefix, 1)[1].lstrip("/")
            parts = tail.split("/")
            if parts and parts[0].isdigit():
                # per-layer subtree: student layer i ← teacher layer map[i]
                i = int(parts[0])
                if i < len(teacher_layers):
                    t_path = path.replace(f"{prefix}/{i}",
                                          f"{prefix}/{teacher_layers[i]}", 1)
                    t = t_flat.get(t_path)
                    if t is not None and t.shape == s_leaf.shape:
                        return t
            t = t_flat.get(path)
            if t is not None and t.ndim == s_leaf.ndim and \
                    t.shape[1:] == s_leaf.shape[1:] and \
                    t.shape[0] != s_leaf.shape[0]:
                # stacked-layer leaf: slice the chosen teacher layers
                idx = jnp.asarray(teacher_layers[:s_leaf.shape[0]])
                return jnp.take(t, idx, axis=0)
        t = t_flat.get(path)
        return t if t is not None and t.shape == s_leaf.shape else s_leaf

    return jax.tree_util.tree_map_with_path(pick, student_params)
