"""Compression config keys — same JSON schema as reference
``deepspeed/compression/constants.py`` (so existing configs run unmodified)."""

COMPRESSION_TRAINING = "compression_training"
SHARED_PARAMETERS = "shared_parameters"
DIFFERENT_GROUPS = "different_groups"

WEIGHT_QUANTIZATION = "weight_quantization"
ACTIVATION_QUANTIZATION = "activation_quantization"
SPARSE_PRUNING = "sparse_pruning"
ROW_PRUNING = "row_pruning"
HEAD_PRUNING = "head_pruning"
CHANNEL_PRUNING = "channel_pruning"
LAYER_REDUCTION = "layer_reduction"

ENABLED = "enabled"
SCHEDULE_OFFSET = "schedule_offset"
SCHEDULE_OFFSET_END = "schedule_offset_end"
METHOD = "method"
QUANTIZE_GROUPS = "quantize_groups"
QUANTIZATION_TYPE = "quantization_type"
ROUNDING = "rounding"
NUM_HEADS = "num_heads"

GROUP_PARAMS = "params"
GROUP_MODULES = "modules"
GROUP_RELATED_MODULES = "related_modules"

START_BITS = "start_bits"
TARGET_BITS = "target_bits"
QUANTIZATION_PERIOD = "quantization_period"
BITS = "bits"
DENSE_RATIO = "dense_ratio"

KEEP_NUMBER_LAYERS = "keep_number_layers"
MODULE_NAME_PREFIX = "module_name_prefix"
TEACHER_LAYER = "teacher_layer"
OTHER_MODULE_NAME = "other_module_name"
