"""Collective micro-benchmark — the ``ds_bench`` CLI.

Reference: ``bin/ds_bench`` forwards to the DeepSpeedExamples communication
suite (all_reduce/all_gather/all_to_all/pt2pt sweeps printing algbw/busbw
per size, nccl-tests conventions).  Here the sweep runs in-process over the
mesh's collectives (psum / all_gather / all_to_all / ppermute on a chosen
axis), with the same bandwidth accounting as ``utils/comms_logging.get_bw``
— plus the collectives-engine variants (hierarchical all-reduce, quantized
all-gather/reduce-scatter, 2-hop hierarchical-quantized reduce-scatter)
so the comm trajectory of ``comm_optimizations`` configs is measurable.

    ds_bench                       # sweep all ops over the dp axis
    ds_bench --op quant_all_gather --axis dp --maxsize 28
    ds_bench --mesh dp=4,tp=2      # explicit mesh factorization
    ds_bench --json out.json       # machine-readable rows (BENCH_*.json food)

Prints one table row per (op, size): logical bytes, wire bytes (what the
bottleneck link actually carries — post-quantization payload + scales),
latency, algbw, busbw.  Bandwidths are computed from WIRE bytes.
"""

import argparse
import json
import os
import time

import numpy as np


OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all", "pt2pt")
# collectives-engine variants (comm/collectives/): hierarchy + quantization
ENGINE_OPS = ("hier_all_reduce", "quant_all_gather", "quant_reduce_scatter",
              "hier_quant_reduce_scatter")
ALL_OPS = OPS + ENGINE_OPS

WIRE_FORMAT = "int8"
GROUP_SIZE = 2048


def _timed_stats(f, args, iters, warmup, repeat=1):
    """Per-call latency statistics of ``f(*args)``: after ``warmup`` calls,
    time ``repeat`` independent blocks of ``iters`` calls each and return
    ``(median, iqr)`` over the per-block averages.  Single-shot timings on
    small messages are noise-dominated (scheduler jitter, dispatch
    variance) — the median resists outliers and the IQR reports how noisy
    the probe actually was, so a downstream cost model can weigh it.
    ``block_until_ready`` fences the async dispatch; safe with warmup=0."""
    import jax
    out = None
    for _ in range(warmup):
        out = f(*args)
    if out is not None:
        jax.block_until_ready(out)
    samples = []
    for _ in range(max(1, repeat)):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(*args)
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / iters)
    med = float(np.median(samples))
    iqr = float(np.percentile(samples, 75) - np.percentile(samples, 25)) \
        if len(samples) > 1 else 0.0
    return med, iqr


def _timed(f, args, iters, warmup, repeat=1):
    """Median per-call latency (see :func:`_timed_stats`)."""
    return _timed_stats(f, args, iters, warmup, repeat=repeat)[0]


class UnsplittableAxis(ValueError):
    """The axis has no non-trivial (outer, inner) factorization — hier_*
    ops are skipped for it, every other error still fails the bench."""


def _hier(mesh, axis, intra):
    """(smesh, outer_axis, inner_axis, n_out, n_in) for the hier ops: the
    topology layer's split when it can see one, else an even power-of-two
    split so the hierarchical schedule is still measurable on flat/virtual
    meshes (the virtual CPU mesh has no physical topology)."""
    from ..comm.backend import ProcessGroup
    from ..comm.collectives.topology import factor_group
    g = ProcessGroup(mesh, (axis, ))
    h = factor_group(g, intra_node_size=intra)
    if h is not None and len(h.inner_axes) == 1 and len(h.outer_axes) == 1:
        return (h.mesh, h.outer_axes[0], h.inner_axes[0], h.outer_size,
                h.inner_size)
    n = mesh.shape[axis]
    inner = 1
    while inner * inner < n and n % (inner * 2) == 0:
        inner *= 2
    if inner <= 1 or inner >= n:
        # a 1-sized factor on either side is not a hierarchy — measuring it
        # as one would report bogus hier_* rows (e.g. axis size 2)
        raise UnsplittableAxis(
            f"axis {axis!r} (size {n}) has no non-trivial split for "
            "hierarchical ops — pass --intra or use an axis of size ≥ 4")
    from ..comm.collectives.topology import split_mesh
    return (split_mesh(mesh, axis, inner), axis + "_out", axis + "_in",
            n // inner, inner)


def _bench_one(op, axis, nbytes, mesh, iters, warmup, intra=0, repeat=1,
               wire=WIRE_FORMAT, group_size=GROUP_SIZE):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..comm.collectives import quantized as Q

    n = mesh.shape[axis]
    elems = max(n, nbytes // 4 // n * n)  # fp32, divisible by axis size
    x = jnp.arange(elems, dtype=jnp.float32)
    size_bytes = elems * 4
    wire_bytes = size_bytes
    bw_op = op

    def make(fn, m=mesh, in_spec=None, out_spec=None):
        return jax.jit(jax.shard_map(
            fn, mesh=m,
            in_specs=P(axis) if in_spec is None else in_spec,
            out_specs=P(axis) if out_spec is None else out_spec,
            check_vma=False))

    if op == "all_reduce":
        f = make(lambda t: jax.lax.psum(t, axis) / n)
    elif op == "all_gather":
        f = make(lambda t: jax.lax.all_gather(t, axis).reshape(-1)[:t.shape[0]])
    elif op == "reduce_scatter":
        f = make(lambda t: jax.lax.psum_scatter(
            t.reshape(n, -1), axis, scatter_dimension=0,
            tiled=False).reshape(-1))
    elif op == "all_to_all":
        f = make(lambda t: jax.lax.all_to_all(
            t.reshape(n, -1), axis, split_axis=0, concat_axis=0,
            tiled=False).reshape(-1))
    elif op == "pt2pt":
        perm = [(i, (i + 1) % n) for i in range(n)]
        f = make(lambda t: jax.lax.ppermute(t, axis, perm))
        bw_op = "send"
    elif op == "hier_all_reduce":
        from ..comm.collectives.engine import _jit_hier_all_reduce
        from ..comm.reduce_op import ReduceOp
        smesh, out_ax, in_ax, n_out, n_in = _hier(mesh, axis, intra)
        # pad the per-rank block to n_in divisibility via elems choice: elems
        # is divisible by n; require further by n*n_in
        elems = max(n * n_in, elems // (n * n_in) * (n * n_in))
        x = jnp.arange(elems, dtype=jnp.float32)
        size_bytes = elems * 4
        wire_bytes = size_bytes // n_in  # fp payload crossing DCN
        # measure the exact kernel the engine ships, not a re-derivation
        f = _jit_hier_all_reduce(smesh, (in_ax, ), (out_ax, ),
                                 ReduceOp.AVG, n)
        bw_op = "all_reduce"
    elif op == "quant_all_gather":
        f = make(lambda t: Q.quantized_all_gather(
            t, (axis, ), 0, wire, group_size).reshape(-1)[:t.shape[0]],
            out_spec=P())
        wire_bytes = Q.quantized_wire_bytes(elems, wire, group_size)
        bw_op = "all_gather"
    elif op == "quant_reduce_scatter":
        f = make(lambda t: Q.all_to_all_quant_reduce(
            t, (axis, ), 0, n, wire_format=wire,
            group_size=group_size), in_spec=P(), out_spec=P(axis))
        wire_bytes = Q.quantized_wire_bytes(elems, wire, group_size)
        bw_op = "reduce_scatter"
    elif op == "hier_quant_reduce_scatter":
        smesh, out_ax, in_ax, n_out, n_in = _hier(mesh, axis, intra)
        f = make(lambda t: Q.hierarchical_quant_reduce_scatter(
            t, (in_ax, ), (out_ax, ), 0, n_in, n_out,
            wire_format=wire, group_size=group_size),
            m=smesh, in_spec=P(), out_spec=P((in_ax, out_ax)))
        # quantized payload crossing DCN on 1/n_in of the data
        wire_bytes = Q.quantized_wire_bytes(elems // n_in, wire,
                                            group_size)
        bw_op = "reduce_scatter"
    else:
        raise ValueError(op)

    lat, iqr = _timed_stats(f, (x, ), iters, warmup, repeat=repeat)

    from ..utils.comms_logging import calc_bw_log
    algbw, busbw = calc_bw_log(bw_op, wire_bytes, lat, n)
    return size_bytes, wire_bytes, lat, algbw, busbw, iqr


# ------------------------------------------------------------- row schema
def bench_row(**fields):
    """THE uniform ``ds_bench --json`` row: every producer (the op sweep,
    the overlap sweep, :func:`probe_op`, the autotuner's trial archive)
    builds rows through this one constructor, so a field added to the
    schema lands everywhere at once instead of drifting across hand-built
    dict literals.  Unset schema fields are explicit ``None``; extra
    producer-specific keys (overlap accounting, trial names) pass
    through."""
    row = {"op": None, "bytes": None, "wire_bytes": None,
           "latency_us": None, "iqr_us": None, "repeat": None,
           "wire_dtype": None, "algbw_gbps": None, "busbw_gbps": None,
           "bucket_mb": None, "direction": None,
           "overlap_efficiency": None, "exposed_comm_frac": None,
           "mfu": None, "peak_hbm_bytes": None}
    row.update(fields)
    return row


# ------------------------------------------------------------- probe API
def probe_op(op, nbytes, axis="dp", mesh=None, iters=5, warmup=2, repeat=3,
             intra=0, wire=WIRE_FORMAT, group_size=GROUP_SIZE):
    """One in-process micro-probe — the reusable ``ds_bench`` candidate
    machinery the autotuner's topology-probe stage calls directly (no
    subprocess orchestration).  Runs ``op`` at ``nbytes`` with warmup +
    ``repeat`` timed blocks and returns ONE row in the uniform
    ``ds_bench --json`` schema (median ``latency_us`` + ``iqr_us``).

    ``wire`` selects the wire format of the ``quant_*`` /
    ``hier_quant_*`` ops (the per-size probes sweep it); flat ops ignore
    it and report ``wire_dtype: "fp32"``.  Raises
    :class:`UnsplittableAxis` for ``hier_*`` ops on axes with no
    non-trivial split — the caller skips that candidate."""
    from ..utils import groups
    if mesh is None:
        mesh = groups.get_mesh_state().mesh
    size, wire_bytes, lat, algbw, busbw, iqr = _bench_one(
        op, axis, nbytes, mesh, iters, warmup, intra=intra, repeat=repeat,
        wire=wire, group_size=group_size)
    return bench_row(
        op=op, bytes=int(size), wire_bytes=int(wire_bytes),
        latency_us=lat * 1e6, iqr_us=iqr * 1e6, repeat=int(repeat),
        wire_dtype=(wire if "quant" in op else "fp32"),
        algbw_gbps=algbw, busbw_gbps=busbw)


# ------------------------------------------------------------ overlap sweep
# Bucketed comm/compute-overlap candidates (bucket size × wire dtype), in
# BOTH directions: how much of the gradient-reduction time can hide under
# backward compute ("reduce"), and how much of the stage-3 param all-gather
# can hide under forward compute ("gather")?  Feeds the overlap scheduler's
# bucket_mb / prefetch.bucket_mb choices (see docs/overlap.md) the way the
# op sweep feeds wire_dtype.

OVERLAP_BUCKET_MBS = (1.0, 4.0, 16.0)
OVERLAP_WIRES = ("fp32", "int8")
OVERLAP_LAYERS = 8
OVERLAP_DIRECTIONS = ("reduce", "gather")


def _overlap_candidate(mesh, axis, bucket_mb, wire, total_bytes, layers,
                       iters, warmup, recorder=None):
    """Measure one (bucket_mb, wire_dtype) candidate.

    Synthetic backward: a chain of matmul segments (the remaining backward
    compute) + per-layer gradient leaves reduced over ``axis``.  Three
    compiled programs — compute-only, comm-only (per bucket, so the trace
    carries real per-bucket costs), and the bucketed overlapped step where
    bucket *k*'s reduce is fenced to segment *k* of the compute chain via
    ``optimization_barrier`` (grads "materialize" as backward progresses).
    Overlap efficiency = hidden / total comm time, where
    ``hidden = comm − exposed`` and ``exposed = step − compute``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..comm.collectives import quantized as Q
    from ..runtime.zero.overlap import partition_buckets

    n = mesh.shape[axis]
    elems = total_bytes // 4 // layers
    elems = max(n * GROUP_SIZE, elems // (n * GROUP_SIZE) * (n * GROUP_SIZE))
    grads = [jnp.linspace(-1.0, 1.0, elems, dtype=jnp.float32)
             for _ in range(layers)]
    H = 256
    x = jnp.ones((8, H), jnp.float32)
    w = jnp.eye(H, dtype=jnp.float32) * 0.999

    buckets = partition_buckets(
        [(f"layer_{i}", g) for i, g in enumerate(grads)],
        int(bucket_mb * (1 << 20)))

    def reduce_leaf(g):
        if wire == "fp32":
            return jax.lax.psum_scatter(g, axis, scatter_dimension=0,
                                        tiled=True)
        return Q.all_to_all_quant_reduce(g, (axis, ), 0, n,
                                         wire_format=wire,
                                         group_size=GROUP_SIZE)

    def sm(fn, out_specs):
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(P(), P(), P()), out_specs=out_specs,
            check_vma=False))

    def compute_only(x, w, grads):
        cur = x
        for _ in range(len(buckets)):
            cur = cur @ w
        return cur

    def overlapped(x, w, grads):
        cur = x
        outs = [None] * len(grads)
        for b in buckets:
            cur = cur @ w
            tied = jax.lax.optimization_barrier(
                tuple(grads[i] for i in b.indices) + (cur, ))
            cur = tied[-1]
            for j, i in enumerate(b.indices):
                outs[i] = reduce_leaf(tied[j])
        return cur, tuple(outs)

    def monolithic(x, w, grads):
        cur = x
        for _ in range(len(buckets)):
            cur = cur @ w
        tied = jax.lax.optimization_barrier(tuple(grads) + (cur, ))
        return tied[-1], tuple(reduce_leaf(g) for g in tied[:-1])

    out_grads = P(axis)  # both hops scatter the reduced shard over axis
    args = (x, w, tuple(grads))
    t_compute = _timed(sm(compute_only, P()), args, iters, warmup)
    fn_step, step_analysis = _aot_with_analysis(
        sm(overlapped, (P(), tuple(out_grads for _ in grads))), args)
    t_step = _timed(fn_step, args, iters, warmup)
    t_mono = _timed(sm(monolithic, (P(), tuple(out_grads for _ in grads))),
                    args, iters, warmup)
    # comm-only, per bucket — the trace carries real per-bucket costs
    t_comm = 0.0
    for b in buckets:
        idx = b.indices

        def bucket_fn(x, w, grads, _idx=idx):
            return tuple(reduce_leaf(grads[i]) for i in _idx)

        fn = sm(bucket_fn, tuple(out_grads for _ in idx))
        if recorder is not None:
            with recorder.bucket_span(b.index, nbytes=b.nbytes):
                t_b = _timed(fn, args, iters, warmup)
        else:
            t_b = _timed(fn, args, iters, warmup)
        t_comm += t_b

    if wire == "fp32":
        wire_bytes = elems * 4 * layers
    else:
        wire_bytes = Q.quantized_wire_bytes(elems, wire, GROUP_SIZE) * layers
    return _candidate_row("reduce", bucket_mb, wire, len(buckets), elems,
                          layers, wire_bytes, t_compute, t_comm, t_step,
                          t_mono,
                          cost_fields=_step_cost_fields(step_analysis,
                                                        t_step))


def _aot_with_analysis(fn, args):
    """Compile a candidate's stepped program ONCE (ahead-of-time) and
    return ``(executable, analysis)`` — the SAME executable is then timed,
    so the cost fields describe exactly what ran and the sweep pays no
    second analysis compile (jit's lazy path + a separate ``analyze_fn``
    would compile every candidate twice).  Falls back to the lazy-jit
    callable with empty analysis where AOT is unavailable."""
    from ..profiling import cost_model
    try:
        compiled = fn.lower(*args).compile()
        return compiled, cost_model.analyze_compiled(compiled)
    except Exception:
        return fn, {"flops": None, "peak_hbm_bytes": None}


def _step_cost_fields(analysis, t_step):
    """Row fields from a stepped program's analysis: mfu = XLA's per-chip
    flop count over the measured step time ÷ peak, plus the static
    peak-HBM estimate (None-safe on backends without the cost model)."""
    from ..profiling import cost_model
    flops = analysis.get("flops")
    return {
        "mfu": cost_model.mfu(flops / t_step
                              if flops and t_step > 0 else None),
        "peak_hbm_bytes": analysis.get("peak_hbm_bytes"),
    }


def _candidate_row(direction, bucket_mb, wire, n_buckets, elems, layers,
                   wire_bytes, t_compute, t_comm, t_step, t_mono,
                   cost_fields=None):
    """Shared overlap-candidate accounting: exposed = step − compute,
    hidden = comm − exposed, efficiency = hidden / comm — identical for
    the reduce (backward) and gather (forward prefetch) directions."""
    exposed = max(0.0, t_step - t_compute)
    hidden = min(t_comm, max(0.0, t_comm - exposed))
    row = dict(cost_fields or {})
    row.update({
        "op": "overlap",
        "direction": direction,
        "bucket_mb": float(bucket_mb),
        "wire_dtype": wire,
        "buckets": n_buckets,
        "bytes": int(elems * 4 * layers),
        "wire_bytes": int(wire_bytes),
        "layers": int(layers),
        "compute_ms": t_compute * 1e3,
        "comm_ms": t_comm * 1e3,
        "step_ms": t_step * 1e3,
        "monolithic_ms": t_mono * 1e3,
        "hidden_ms": hidden * 1e3,
        "exposed_ms": exposed * 1e3,
        "exposed_comm_frac": (exposed / t_step if t_step > 0 else 0.0),
        "overlap_efficiency": (hidden / t_comm if t_comm > 0 else 1.0),
    })
    return row


def _gather_candidate(mesh, axis, bucket_mb, wire, total_bytes, layers,
                      iters, warmup, recorder=None):
    """Measure one forward-direction (bucket_mb, wire_dtype) prefetch
    candidate.

    Synthetic stage-3 forward: per-layer ZeRO-sharded param leaves + a
    matmul chain (the layer compute).  Three compiled programs — compute-
    only, gather-only (per bucket, so the trace carries real per-bucket
    costs), and the prefetched step where segment *k* of the chain is
    fenced to bucket *k*'s gathered params via ``optimization_barrier``
    (the layers that need bucket *k* run once its params arrive, while
    bucket *k+1*'s gather — independent of the chain — may run
    underneath).  ``wire`` = "fp32" is the plain all-gather; anything else
    is the qwZ quantized all-gather at that wire dtype.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..comm.collectives import quantized as Q
    from ..runtime.zero.overlap import partition_prefetch_buckets

    n = mesh.shape[axis]
    elems = total_bytes // 4 // layers
    elems = max(n * GROUP_SIZE, elems // (n * GROUP_SIZE) * (n * GROUP_SIZE))
    params = [jnp.linspace(-1.0, 1.0, elems, dtype=jnp.float32)
              for _ in range(layers)]
    H = 256
    x = jnp.ones((8, H), jnp.float32)
    w = jnp.eye(H, dtype=jnp.float32) * 0.999

    buckets = partition_prefetch_buckets(
        [(f"layer_{i}", p) for i, p in enumerate(params)],
        int(bucket_mb * (1 << 20)))

    def gather_leaf(p):
        if wire == "fp32":
            return jax.lax.all_gather(p, axis, axis=0, tiled=True)
        return Q.quantized_all_gather(p, (axis, ), 0, wire, GROUP_SIZE)

    def sm(fn, out_specs):
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(P(), P(), P(axis)),
            out_specs=out_specs, check_vma=False))

    def compute_only(x, w, params):
        cur = x
        for _ in range(len(buckets)):
            cur = cur @ w
        return cur

    def prefetched(x, w, params):
        cur = x
        full = [None] * len(params)
        for b in buckets:
            gathered = tuple(gather_leaf(params[i]) for i in b.indices)
            tied = jax.lax.optimization_barrier(gathered + (cur, ))
            cur = tied[-1] @ w
            for j, i in enumerate(b.indices):
                full[i] = tied[j]
        return cur, tuple(full)

    def monolithic(x, w, params):
        full = tuple(gather_leaf(p) for p in params)
        tied = jax.lax.optimization_barrier(full + (x, ))
        cur = tied[-1]
        for _ in range(len(buckets)):
            cur = cur @ w
        return cur, tied[:-1]

    out_full = tuple(P() for _ in params)  # gathered: replicated over axis
    args = (x, w, tuple(params))
    t_compute = _timed(sm(compute_only, P()), args, iters, warmup)
    fn_step, step_analysis = _aot_with_analysis(
        sm(prefetched, (P(), out_full)), args)
    t_step = _timed(fn_step, args, iters, warmup)
    t_mono = _timed(sm(monolithic, (P(), out_full)), args, iters, warmup)
    t_comm = 0.0
    for b in buckets:
        idx = b.indices

        def bucket_fn(x, w, params, _idx=idx):
            return tuple(gather_leaf(params[i]) for i in _idx)

        fn = sm(bucket_fn, tuple(P() for _ in idx))
        if recorder is not None:
            with recorder.bucket_span(b.index, kind="param_gather",
                                      nbytes=b.nbytes):
                t_b = _timed(fn, args, iters, warmup)
        else:
            t_b = _timed(fn, args, iters, warmup)
        t_comm += t_b

    if wire == "fp32":
        wire_bytes = elems * 4 * layers
    else:
        wire_bytes = Q.quantized_wire_bytes(elems, wire, GROUP_SIZE) * layers
    return _candidate_row("gather", bucket_mb, wire, len(buckets), elems,
                          layers, wire_bytes, t_compute, t_comm, t_step,
                          t_mono,
                          cost_fields=_step_cost_fields(step_analysis,
                                                        t_step))


def run_overlap_sweep(axis="dp", mesh=None, bucket_mbs=OVERLAP_BUCKET_MBS,
                      wires=OVERLAP_WIRES, total_mb=8.0,
                      layers=OVERLAP_LAYERS, iters=10, warmup=2,
                      print_fn=print, recorder=None,
                      directions=OVERLAP_DIRECTIONS):
    """bucket_mb × wire_dtype sweep of the bucketed overlap schedulers, one
    pass per ``direction``: "reduce" (backward grad reduce-scatter) and
    "gather" (forward stage-3 param all-gather prefetch).  Returns
    candidate dicts (the ``--json`` rows / comm_summary ``overlap``
    section), each tagged with its ``direction``."""
    from ..utils import groups
    if mesh is None:
        mesh = groups.get_mesh_state().mesh
    unknown = [d for d in directions if d not in OVERLAP_DIRECTIONS]
    if unknown:
        # a --overlap-directions typo must not burn a sweep under a
        # mislabeled tag that every report then silently drops
        raise ValueError(
            f"unknown overlap sweep direction(s) {unknown!r} — valid: "
            f"{', '.join(OVERLAP_DIRECTIONS)}")
    out = []
    for direction in directions:
        measure = (_overlap_candidate if direction == "reduce"
                   else _gather_candidate)
        # the hidden/exposed comm-event rows use the base op the direction
        # actually sweeps, in the op[variant] vocabulary of training traces
        base_op = "reduce_scatter" if direction == "reduce" else "all_gather"
        var_prefix = "overlap" if direction == "reduce" else "prefetch"
        print_fn(f"# overlap sweep: direction={direction} "
                 f"mesh={dict(mesh.shape)} axis={axis} "
                 f"total={total_mb}MiB layers={layers}")
        print_fn(f"{'bucket_mb':>10}{'wire':>8}{'buckets':>9}"
                 f"{'compute_ms':>12}"
                 f"{'comm_ms':>10}{'step_ms':>10}{'mono_ms':>10}"
                 f"{'exposed_frac':>14}{'overlap_eff':>13}")
        cands = []
        for wire in wires:
            for mb in bucket_mbs:
                c = measure(mesh, axis, mb, wire,
                            int(total_mb * (1 << 20)), layers,
                            iters, warmup, recorder=recorder)
                cands.append(c)
                if recorder is not None:
                    # exposed/hidden split rides the comm-event spine
                    variant = f"{var_prefix}_{wire}_b{mb:g}"
                    recorder.comm_event(base_op, variant, c["bytes"],
                                        c["wire_bytes"],
                                        c["exposed_ms"] / 1e3,
                                        world_size=mesh.shape[axis])
                    recorder.comm_event(base_op, variant, 0,
                                        0, c["hidden_ms"] / 1e3,
                                        world_size=mesh.shape[axis],
                                        exposed=False)
                print_fn(f"{mb:>10g}{wire:>8}{c['buckets']:>9}"
                         f"{c['compute_ms']:>12.3f}{c['comm_ms']:>10.3f}"
                         f"{c['step_ms']:>10.3f}{c['monolithic_ms']:>10.3f}"
                         f"{c['exposed_comm_frac']:>14.3f}"
                         f"{c['overlap_efficiency']:>13.3f}")
        best = max(cands, key=lambda c: c["overlap_efficiency"])
        print_fn(f"# best {direction}: bucket_mb={best['bucket_mb']:g} "
                 f"wire={best['wire_dtype']} "
                 f"overlap_efficiency={best['overlap_efficiency']:.3f}")
        out.extend(cands)
    return out


# ---------------------------------------------------------------- moe sweep
# Expert-dispatch candidates (E × capacity_factor × wire dtype): how much
# does the quantized/hierarchical a2a exchange save over the GSPMD
# constraint reshard for the hardest collective in the stack?  Feeds
# ``moe.wire_dtype`` the way the op sweep feeds ``wire_dtype`` (docs/moe.md).

MOE_EXPERTS = (8, 16)
MOE_CAPACITY_FACTORS = (1.0, 2.0)
MOE_WIRES = ("fp32", "int8")
MOE_TOKENS = 4096
MOE_HIDDEN = 256


def _moe_candidate(mesh, experts, capacity_factor, wire, tokens, hidden,
                   iters, warmup, repeat):
    """Measure one (E, capacity_factor, wire) expert-dispatch candidate:
    the full dispatch → (trivial) expert → combine round trip, GSPMD
    constraint path for wire None vs the manual exchange at ``wire``."""
    import jax
    import jax.numpy as jnp
    from ..moe import engine as moe_engine
    from ..moe.engine import MoeOptions, expert_dispatch_wire_bytes
    from ..moe.sharded_moe import top1gating

    ep = mesh.shape.get("ep", 1)
    E = experts - experts % ep if experts % ep else experts
    if E < ep:
        # experts < ep rounds to 0 and the gate's capacity math divides by
        # E — skip with guidance instead of a cryptic ZeroDivisionError
        raise UnsplittableAxis(
            f"experts={experts} cannot shard over ep={ep} (need >= ep, "
            "divisible) — raise --moe-experts or shrink the ep axis")
    rngk = jax.random.PRNGKey(0)
    x = jax.random.normal(rngk, (tokens, hidden), jnp.float32)
    logits = jax.random.normal(jax.random.fold_in(rngk, 1), (tokens, E),
                               jnp.float32)
    l_aux, combine, dispatch, counts = top1gating(
        logits, capacity_factor=capacity_factor)
    C = combine.shape[-1]
    kept = float(jnp.sum(dispatch.astype(jnp.float32)))
    drop_fraction = 1.0 - kept / tokens
    mean_c = max(1e-9, kept / E)
    imbalance = float(jnp.max(counts.astype(jnp.float32))) / mean_c
    expert_fn = lambda d: d * 1.0009765625  # trivial: comm-dominant

    # snapshot the FULL dispatcher state (options + comm view): a live
    # engine may have installed a wire ladder this sweep must hand back
    prev = moe_engine.snapshot()
    opts = None if wire is None else MoeOptions(
        enabled=True, quantized_dispatch=True, wire_dtype=wire,
        quantization_group_size=GROUP_SIZE)
    moe_engine.configure(opts)
    payload = E * C * hidden
    try:
        if opts is not None:
            # report what the timed exchange ACTUALLY moves: the same
            # resolution the dispatcher uses (ladder rung + hierarchy —
            # the 2-hop variant crosses the bottleneck link with 1/n_inner
            # of the data)
            _, _, _, wire_bytes = moe_engine.resolve_exchange(
                mesh, opts, "ep", payload)
        else:
            wire_bytes = expert_dispatch_wire_bytes(payload, "fp32",
                                                    GROUP_SIZE)
        fn = jax.jit(lambda t, cm, dm: moe_engine.dispatch_combine(
            t, cm, dm, expert_fn, mesh=mesh))
        lat, iqr = _timed_stats(fn, (x, combine, dispatch), iters, warmup,
                                repeat=repeat)
    finally:
        moe_engine.restore(prev)
    return bench_row(
        op="moe_dispatch", direction="moe",
        wire_dtype=(wire if wire is not None else "gspmd"),
        bytes=int(payload * 4), wire_bytes=int(wire_bytes),
        latency_us=lat * 1e6, iqr_us=iqr * 1e6, repeat=int(repeat),
        experts=int(E), capacity_factor=float(capacity_factor),
        capacity=int(C), tokens=int(tokens),
        drop_fraction=float(drop_fraction),
        load_imbalance=float(imbalance),
        aux_loss=float(l_aux))


def run_moe_sweep(mesh=None, experts=MOE_EXPERTS,
                  capacity_factors=MOE_CAPACITY_FACTORS, wires=MOE_WIRES,
                  tokens=MOE_TOKENS, hidden=MOE_HIDDEN, iters=10, warmup=2,
                  repeat=3, print_fn=print, recorder=None):
    """E × capacity_factor × wire sweep of the expert-dispatch exchange.
    Every candidate also runs the GSPMD constraint baseline once per (E,
    cf) so the manual variants have an in-row comparison.  Returns uniform
    ``bench_row`` dicts tagged ``direction: "moe"``."""
    from ..utils import groups
    if mesh is None:
        mesh = groups.get_mesh_state().mesh
    if mesh.shape.get("ep", 1) < 2:
        raise SystemExit(
            f"moe sweep needs an expert-parallel mesh (ep >= 2), got "
            f"{dict(mesh.shape)} — pass e.g. --mesh dp=2,ep=4")
    print_fn(f"# moe dispatch sweep: mesh={dict(mesh.shape)} "
             f"tokens={tokens} hidden={hidden}")
    print_fn(f"{'experts':>8}{'cf':>6}{'wire':>8}{'capacity':>10}"
             f"{'drop_frac':>11}{'imbalance':>11}{'wire_bytes':>12}"
             f"{'latency_us':>12}{'iqr_us':>9}")
    rows = []
    ep = mesh.shape.get("ep", 1)
    for E in experts:
        if E - E % ep < ep:
            # experts < ep rounds to an empty expert stack — skip the whole
            # E loudly instead of dying in the gate's capacity division
            print_fn(f"# E={E}: skipped (cannot shard over ep={ep}; "
                     "raise --moe-experts or shrink the ep axis)")
            continue
        if E % ep:
            # no silent caps: the rounded-down count is what actually runs
            # (and what the emitted rows carry as `experts`)
            print_fn(f"# E={E}: rounded down to {E - E % ep} "
                     f"(must divide ep={ep})")
        for cf in capacity_factors:
            for wire in (None, ) + tuple(wires):
                span = (recorder.span(
                    f"moe_dispatch/{E}x{cf:g}/{wire or 'gspmd'}",
                    cat="bench") if recorder is not None else None)
                if span is not None:
                    with span:
                        c = _moe_candidate(mesh, E, cf, wire, tokens,
                                           hidden, iters, warmup, repeat)
                else:
                    c = _moe_candidate(mesh, E, cf, wire, tokens, hidden,
                                       iters, warmup, repeat)
                rows.append(c)
                if recorder is not None and wire is not None:
                    recorder.comm_event(
                        "all_to_all", f"moe_q_{wire}", c["bytes"],
                        c["wire_bytes"], c["latency_us"] / 1e6,
                        world_size=mesh.shape.get("ep", 1))
                print_fn(f"{c['experts']:>8}{c['capacity_factor']:>6g}"
                         f"{c['wire_dtype']:>8}{c['capacity']:>10}"
                         f"{c['drop_fraction']:>11.3f}"
                         f"{c['load_imbalance']:>11.2f}"
                         f"{c['wire_bytes']:>12}"
                         f"{c['latency_us']:>12.1f}{c['iqr_us']:>9.1f}")
    best = best_moe_candidate(rows)
    if best is not None:
        r, speedup = best
        print_fn(f"# best manual dispatch: wire={r['wire_dtype']} "
                 f"E={r['experts']} cf={r['capacity_factor']:g} "
                 f"({speedup:.2f}x vs gspmd)")
    return rows


def best_moe_candidate(rows):
    """(row, speedup) of the manual-dispatch wire with the best PER-CELL
    speedup over its own (E, capacity_factor) gspmd baseline, or None when
    no manual wire beats its baseline — raw cross-cell latency would let
    the smallest-payload cell decide (same rule as
    ``fold_sweeps.aggregate_moe``'s suggestion)."""
    baselines = {(r.get("experts"), r.get("capacity_factor")):
                 r.get("latency_us")
                 for r in rows if r.get("wire_dtype") == "gspmd"}
    best, best_speedup = None, 1.0
    for r in rows:
        if r.get("wire_dtype") in ("gspmd", None):
            continue
        base = baselines.get((r.get("experts"), r.get("capacity_factor")))
        lat = r.get("latency_us")
        if not base or not lat:
            continue
        speedup = base / lat
        if speedup > best_speedup:
            best, best_speedup = r, speedup
    return None if best is None else (best, best_speedup)


# ------------------------------------------------------------ zero-mode lane
# The three micro-step architectures that can carry a ZeRO training step
# (ISSUE 15, docs/zero.md "GSPMD-first ZeRO"), measured against each other
# on a REAL engine micro-step (not a synthetic proxy):
#   flat_manual — the legacy full-manual shard_map qgZ micro
#                 (comm_optimizations.zero_mode: "flat_manual");
#   gspmd       — the pure GSPMD micro, no quantization (the flat-wire
#                 upper bound XLA schedules end to end);
#   gspmd_q     — the GSPMD-first micro with quantized islands (the
#                 default qgZ path).
# bench LANES, not config values — runtime/zero/gspmd.ZERO_MODES
# (the comm_optimizations.zero_mode validator) accepts only
# "gspmd"/"flat_manual"; "gspmd_q" names the quantized-islands lane
ZERO_MODE_LANES = ("flat_manual", "gspmd", "gspmd_q")
ZERO_MODE_WIRES = ("int8", )
ZERO_MODE_HIDDEN = 256
ZERO_MODE_LAYERS = 4


def _zero_mode_config(mode, stage, wire):
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "sgd", "params": {"lr": 0.1}},
        "zero_optimization": {"stage": stage,
                              "stage3_param_persistence_threshold": 0},
        "mesh": {"dp": -1},
    }
    if mode != "gspmd":
        cfg["comm_optimizations"] = {
            "enabled": True, "quantized_gradients": True,
            "wire_dtype": wire, "quantization_group_size": GROUP_SIZE,
            **({"zero_mode": "flat_manual"} if mode == "flat_manual"
               else {}),
        }
    return cfg


def _zero_mode_candidate(mode, stage, wire, hidden, nlayers, iters, warmup,
                         repeat):
    """Time one zero-mode lane: build a real engine with that micro-step
    architecture, AOT-compile its ACTUAL micro (the same executable
    training runs) and report the median step latency + compiled-cost
    fields.  One uniform ``bench_row`` with ``direction: "zero_mode"``."""
    import jax
    import deepspeed_tpu
    from ..comm.collectives import quantized as Q
    from ..utils import groups

    groups.reset_mesh()
    deepspeed_tpu.comm.destroy_process_group()
    rng = np.random.RandomState(0)
    params = {}
    for i in range(nlayers):
        params[f"layer_{i}"] = {
            "w": (rng.standard_normal((hidden, hidden)) * 0.05
                  ).astype("float32"),
            "b": np.zeros((hidden, ), "float32"),
        }

    def apply_fn(p, x, y):
        import jax.numpy as jnp
        h = x
        for i in range(nlayers):
            lp = p[f"layer_{i}"]
            h = jnp.tanh(h @ lp["w"] + lp["b"])
        return jnp.mean((h - y) ** 2)

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=apply_fn, model_parameters=params,
        config=_zero_mode_config(mode, stage, wire))
    try:
        xs = rng.standard_normal(
            (8 * engine.dp_world_size, hidden)).astype("float32")
        ys = np.tanh(xs * 0.5).astype("float32")
        inputs = engine.shard_batch(xs, ys)
        micro = engine._micro_step_fn()
        args = (engine.params, engine.scale_state.scale, inputs)
        fn, analysis = _aot_with_analysis(jax.jit(micro), args)
        lat, iqr = _timed_stats(fn, args, iters, warmup, repeat=repeat)
        variant = engine._micro_variant()
        grad_elems = sum(int(np.prod(x.shape))
                         for x in jax.tree_util.tree_leaves(params))
        if mode == "gspmd":
            wire_bytes = grad_elems * 4
        else:
            wire_bytes = Q.quantized_wire_bytes(grad_elems, wire,
                                                GROUP_SIZE)
        return bench_row(
            op="zero_micro_step", direction="zero_mode",
            zero_mode=mode, micro_variant=variant, stage=int(stage),
            wire_dtype=(wire if mode != "gspmd" else "fp32"),
            bytes=int(grad_elems * 4), wire_bytes=int(wire_bytes),
            latency_us=lat * 1e6, iqr_us=iqr * 1e6, repeat=int(repeat),
            # the lane ALWAYS runs on its own pure-dp mesh over all
            # devices (the three micros differ only in the dp exchange) —
            # recorded per row because the payload-level "mesh" describes
            # the surrounding op sweeps, not these engines
            mesh={"dp": int(engine.dp_world_size)},
            **_step_cost_fields(analysis, lat))
    finally:
        groups.reset_mesh()
        deepspeed_tpu.comm.destroy_process_group()


def run_zero_mode_sweep(mesh=None, stages=(2, ), wires=ZERO_MODE_WIRES,
                        hidden=ZERO_MODE_HIDDEN, layers=ZERO_MODE_LAYERS,
                        iters=5, warmup=2, repeat=3, print_fn=print,
                        recorder=None):
    """The three-way flat-manual / GSPMD / GSPMD+quantized-islands lane
    (``ds_bench --zero-mode``): one real engine micro-step per
    architecture, per stage × wire.  Returns uniform ``bench_row`` dicts
    tagged ``direction: "zero_mode"`` — ``fold_sweeps.
    aggregate_zero_mode`` folds archives and the autotuner searches the
    same knob (``comm_optimizations.zero_mode``)."""
    import contextlib

    import jax
    from ..utils import groups
    if len(jax.devices()) < 2:
        raise SystemExit("zero-mode lane needs >= 2 devices (the three "
                         "micros differ only in how the dp exchange runs)")
    # the lane rebuilds engines (and thus meshes) per candidate; remember
    # the bench mesh so the other sweeps in this invocation still see it
    orig = (dict(mesh.shape) if mesh is not None
            else dict(groups.get_mesh_state().mesh.shape))
    print_fn(f"# zero-mode lane: devices={len(jax.devices())} "
             f"hidden={hidden} layers={layers} "
             f"(flat_manual / gspmd / gspmd_q)")
    print_fn(f"{'stage':>6}{'mode':>13}{'wire':>7}{'variant':>18}"
             f"{'latency_us':>12}{'iqr_us':>9}{'wire_bytes':>12}")
    rows = []
    try:
        for stage in stages:
            for wire in wires:
                for mode in ZERO_MODE_LANES:
                    span = (recorder.span(
                        f"zero_mode/{stage}/{wire}/{mode}", cat="bench")
                        if recorder is not None
                        else contextlib.nullcontext())
                    with span:
                        c = _zero_mode_candidate(mode, stage, wire, hidden,
                                                 layers, iters, warmup,
                                                 repeat)
                    rows.append(c)
                    print_fn(f"{c['stage']:>6}{c['zero_mode']:>13}"
                             f"{c['wire_dtype']:>7}"
                             f"{c['micro_variant']:>18}"
                             f"{c['latency_us']:>12.1f}"
                             f"{c['iqr_us']:>9.1f}"
                             f"{c['wire_bytes']:>12}")
                fm = next(r for r in rows[-len(ZERO_MODE_LANES):]
                          if r["zero_mode"] == "flat_manual")
                for r in rows[-len(ZERO_MODE_LANES):]:
                    if r["zero_mode"] != "flat_manual" and r["latency_us"]:
                        print_fn(
                            f"# z{stage}/{wire} {r['zero_mode']}: "
                            f"{fm['latency_us'] / r['latency_us']:.2f}x "
                            f"vs flat_manual")
    finally:
        # restore the bench mesh for whatever sweeps follow
        groups.reset_mesh()
        import deepspeed_tpu
        deepspeed_tpu.comm.destroy_process_group()
        groups.initialize_mesh(**{k: int(v) for k, v in orig.items()})
    return rows


# engine-variant op → (facade op, comms-logging variant tag) so traced
# sweeps use the same ``op[variant]`` vocabulary as training traces
_TRACE_VARIANTS = {
    "hier_all_reduce": ("all_reduce", "hier"),
    "quant_all_gather": ("all_gather", f"q_{WIRE_FORMAT}"),
    "quant_reduce_scatter": ("reduce_scatter", f"q_{WIRE_FORMAT}"),
    "hier_quant_reduce_scatter": ("reduce_scatter", f"hier_q_{WIRE_FORMAT}"),
}


def run(ops=ALL_OPS, axis="dp", minsize=16, maxsize=26, mesh_spec=None,
        iters=20, warmup=3, print_fn=print, intra=0, json_path=None,
        trace_dir=None, overlap=False, overlap_total_mb=8.0,
        overlap_bucket_mbs=OVERLAP_BUCKET_MBS, overlap_wires=OVERLAP_WIRES,
        overlap_directions=OVERLAP_DIRECTIONS, repeat=3, moe=False,
        moe_experts=MOE_EXPERTS, moe_capacity_factors=MOE_CAPACITY_FACTORS,
        moe_wires=MOE_WIRES, moe_tokens=MOE_TOKENS, zero_mode=False,
        zero_mode_stages=(2, ), zero_mode_wires=ZERO_MODE_WIRES):
    """Sweep collectives over powers-of-two message sizes.  Returns rows of
    (op, bytes, wire_bytes, latency_s, algbw_gbps, busbw_gbps, iqr_s) —
    latency is the MEDIAN over ``repeat`` timed blocks, iqr their
    interquartile range (see ``_timed_stats``); with ``json_path``, also
    writes them as machine-readable JSON; with ``trace_dir``, archives
    telemetry artifacts (chrome trace + per-variant comm attribution)
    alongside the sweep output so a BENCH_*.json row can be traced back to
    what actually ran."""
    from ..utils import groups
    if mesh_spec:
        kw = {}
        for part in mesh_spec.split(","):
            k, v = part.split("=")
            kw[k] = int(v)
        groups.reset_mesh()
        groups.initialize_mesh(**kw)
    mesh = groups.get_mesh_state().mesh
    # the op/overlap sweeps run collectives over `axis`; a moe-only
    # invocation keys on the ep axis instead (run_moe_sweep guards it)
    needs_axis = bool(ops) or overlap
    if needs_axis and mesh.shape.get(axis, 1) < 2:
        raise SystemExit(
            f"axis {axis!r} has size {mesh.shape.get(axis, 1)} on mesh "
            f"{dict(mesh.shape)} — nothing to benchmark (pass --mesh)")
    recorder = None
    if trace_dir:
        from ..telemetry import TraceRecorder
        recorder = TraceRecorder(trace_dir, rank=0)
    rows = []
    print_fn(f"# mesh={dict(mesh.shape)} axis={axis} dtype=fp32 "
             f"wire={WIRE_FORMAT} repeat={repeat}")
    print_fn(f"{'op':<28}{'bytes':>12}{'wire_bytes':>12}{'latency_us':>14}"
             f"{'iqr_us':>10}{'algbw_Gbps':>12}{'busbw_Gbps':>12}")
    for op in ops:
        for p in range(minsize, maxsize + 1, 2):
            try:
                if recorder is not None:
                    with recorder.span(f"{op}/{1 << p}", cat="bench"):
                        size, wire, lat, algbw, busbw, iqr = _bench_one(
                            op, axis, 1 << p, mesh, iters, warmup,
                            intra=intra, repeat=repeat)
                else:
                    size, wire, lat, algbw, busbw, iqr = _bench_one(
                        op, axis, 1 << p, mesh, iters, warmup, intra=intra,
                        repeat=repeat)
            except UnsplittableAxis as e:
                # hier_* on an unsplittable axis: note and keep sweeping the
                # other ops (any other error still fails the bench loudly)
                print_fn(f"# {op}: skipped ({e})")
                break
            rows.append((op, size, wire, lat, algbw, busbw, iqr))
            if recorder is not None:
                base, variant = _TRACE_VARIANTS.get(op, (op, None))
                recorder.comm_event(base, variant, size, wire, lat,
                                    world_size=mesh.shape[axis])
            print_fn(f"{op:<28}{size:>12}{wire:>12}{lat * 1e6:>14.1f}"
                     f"{iqr * 1e6:>10.1f}{algbw:>12.2f}{busbw:>12.2f}")
    overlap_rows = []
    if overlap:
        overlap_rows = run_overlap_sweep(
            axis=axis, mesh=mesh, bucket_mbs=overlap_bucket_mbs,
            wires=overlap_wires, total_mb=overlap_total_mb,
            iters=max(2, iters // 2), warmup=warmup, print_fn=print_fn,
            recorder=recorder, directions=overlap_directions)
    moe_rows = []
    if moe:
        moe_rows = run_moe_sweep(
            mesh=mesh, experts=moe_experts,
            capacity_factors=moe_capacity_factors, wires=moe_wires,
            tokens=moe_tokens, iters=max(2, iters // 2), warmup=warmup,
            repeat=repeat, print_fn=print_fn, recorder=recorder)
    zero_mode_rows = []
    if zero_mode:
        zero_mode_rows = run_zero_mode_sweep(
            mesh=mesh, stages=zero_mode_stages, wires=zero_mode_wires,
            iters=max(2, iters // 4), warmup=warmup, repeat=repeat,
            print_fn=print_fn, recorder=recorder)
    if json_path:
        # uniform row schema (bench_row): overlap/stat fields present on
        # every row so BENCH_* aggregation (fold_sweeps) never key-errors
        json_rows = [bench_row(op=op, bytes=int(size),
                               wire_bytes=int(wire), latency_us=lat * 1e6,
                               iqr_us=iqr * 1e6, repeat=repeat,
                               wire_dtype=(WIRE_FORMAT if "quant" in op
                                           else "fp32"),
                               algbw_gbps=algbw, busbw_gbps=busbw)
                     for op, size, wire, lat, algbw, busbw, iqr in rows]
        for c in overlap_rows:
            # overlap candidates time single blocks, not `repeat` medians —
            # stamping the op sweep's repeat here would let downstream
            # aggregation weigh them as multi-block medians they are not
            json_rows.append(bench_row(**c, latency_us=c["step_ms"] * 1e3))
        json_rows.extend(moe_rows)  # already uniform bench_row dicts
        json_rows.extend(zero_mode_rows)  # uniform, direction:"zero_mode"
        payload = {
            "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
            "axis": axis,
            "dtype": "fp32",
            "wire_format": WIRE_FORMAT,
            "quantization_group_size": GROUP_SIZE,
            "rows": json_rows,
        }
        with open(json_path, "w") as fh:
            json.dump(payload, fh, indent=2)
        print_fn(f"# wrote {len(json_rows)} rows to {json_path}")
    if recorder is not None:
        summary_path = os.path.join(recorder.trace_dir, "comm_summary.json")
        summary = {"mesh": {k: int(v)
                            for k, v in dict(mesh.shape).items()},
                   "axis": axis, "ops": recorder.comm_summary()}
        if overlap_rows:
            summary["overlap"] = overlap_rows
        if moe_rows:
            summary["moe"] = moe_rows
        if zero_mode_rows:
            summary["zero_mode"] = zero_mode_rows
        with open(summary_path, "w") as fh:
            json.dump(summary, fh, indent=2)
        recorder.close()
        print_fn(f"# archived trace + comm attribution under "
                 f"{recorder.trace_dir}")
    return rows


def cli_main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds_bench", description="collective micro-benchmarks over the "
        "device mesh (reference bin/ds_bench), incl. hierarchical/quantized "
        "engine variants")
    ap.add_argument("--op", choices=ALL_OPS, default=None,
                    help="single op (default: all)")
    ap.add_argument("--axis", default="dp")
    ap.add_argument("--mesh", default=None,
                    help="mesh factorization, e.g. dp=4,tp=2")
    ap.add_argument("--minsize", type=int, default=16,
                    help="log2 of smallest message (default 16 = 64KiB)")
    ap.add_argument("--maxsize", type=int, default=26,
                    help="log2 of largest message (default 26 = 64MiB)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--repeat", type=int, default=3,
                    help="timed blocks per row; reported latency is their "
                    "MEDIAN and iqr_us their interquartile range (small-"
                    "message single-shot timings are noise-dominated)")
    ap.add_argument("--intra", type=int, default=0,
                    help="intra-node size for hier_* ops (0 = topology "
                    "auto-detect, falling back to an even split)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="also write machine-readable rows to PATH")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="archive telemetry artifacts (chrome trace + "
                    "per-variant comm attribution) under DIR alongside "
                    "the --json rows")
    ap.add_argument("--overlap", action="store_true",
                    help="also sweep the bucketed overlap schedulers "
                    "(bucket_mb × wire dtype, reduce AND gather "
                    "directions; docs/overlap.md)")
    ap.add_argument("--overlap-directions", default=None,
                    metavar="D[,D]",
                    help="comma-separated overlap sweep directions "
                    "(default reduce,gather)")
    ap.add_argument("--overlap-total-mb", type=float, default=8.0,
                    help="total gradient payload for the overlap sweep")
    ap.add_argument("--overlap-buckets", default=None, metavar="MB,MB,…",
                    help="comma-separated bucket_mb candidates "
                    "(default 1,4,16)")
    ap.add_argument("--overlap-wires", default=None, metavar="W,W",
                    help="comma-separated wire dtypes for the overlap "
                    "sweep (default fp32,int8)")
    ap.add_argument("--moe", action="store_true",
                    help="also sweep the expert-dispatch exchange "
                    "(experts × capacity_factor × wire dtype on the ep "
                    "axis; needs an ep>=2 mesh — docs/moe.md)")
    ap.add_argument("--moe-experts", default=None, metavar="E,E",
                    help="comma-separated expert counts (default 8,16)")
    ap.add_argument("--moe-capacity-factors", default=None, metavar="F,F",
                    help="comma-separated capacity factors (default 1,2)")
    ap.add_argument("--moe-wires", default=None, metavar="W,W",
                    help="comma-separated dispatch wire dtypes "
                    "(default fp32,int8; the GSPMD baseline always runs)")
    ap.add_argument("--moe-tokens", type=int, default=MOE_TOKENS,
                    help="tokens per dispatch for the moe sweep")
    ap.add_argument("--zero-mode", action="store_true",
                    help="also run the three-way ZeRO micro-step lane "
                    "(flat-manual / GSPMD / GSPMD+quantized-islands on a "
                    "real engine micro — docs/zero.md)")
    ap.add_argument("--zero-mode-stages", default=None, metavar="S,S",
                    help="comma-separated ZeRO stages for the zero-mode "
                    "lane (default 2)")
    ap.add_argument("--zero-mode-wires", default=None, metavar="W,W",
                    help="comma-separated qgZ wire dtypes for the "
                    "zero-mode lane (default int8)")
    args = ap.parse_args(argv)
    # --overlap/--moe/--zero-mode alone sweep just their lane; add --op to
    # also run the collective op sweep in the same invocation
    default_ops = () if (args.overlap or args.moe or args.zero_mode) \
        else ALL_OPS
    run(ops=(args.op, ) if args.op else default_ops, axis=args.axis,
        minsize=args.minsize, maxsize=args.maxsize, mesh_spec=args.mesh,
        iters=args.iters, warmup=args.warmup, repeat=args.repeat,
        intra=args.intra,
        json_path=args.json, trace_dir=args.trace, overlap=args.overlap,
        overlap_total_mb=args.overlap_total_mb,
        overlap_bucket_mbs=(tuple(float(x) for x in
                                  args.overlap_buckets.split(","))
                            if args.overlap_buckets else OVERLAP_BUCKET_MBS),
        overlap_wires=(tuple(args.overlap_wires.split(","))
                       if args.overlap_wires else OVERLAP_WIRES),
        overlap_directions=(tuple(args.overlap_directions.split(","))
                            if args.overlap_directions
                            else OVERLAP_DIRECTIONS),
        moe=args.moe,
        moe_experts=(tuple(int(x) for x in args.moe_experts.split(","))
                     if args.moe_experts else MOE_EXPERTS),
        moe_capacity_factors=(
            tuple(float(x) for x in args.moe_capacity_factors.split(","))
            if args.moe_capacity_factors else MOE_CAPACITY_FACTORS),
        moe_wires=(tuple(args.moe_wires.split(","))
                   if args.moe_wires else MOE_WIRES),
        moe_tokens=args.moe_tokens,
        zero_mode=args.zero_mode,
        zero_mode_stages=(tuple(int(x) for x in
                                args.zero_mode_stages.split(","))
                          if args.zero_mode_stages else (2, )),
        zero_mode_wires=(tuple(args.zero_mode_wires.split(","))
                         if args.zero_mode_wires else ZERO_MODE_WIRES))


if __name__ == "__main__":
    cli_main()
