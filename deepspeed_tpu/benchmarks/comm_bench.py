"""Collective micro-benchmark — the ``ds_bench`` CLI.

Reference: ``bin/ds_bench`` forwards to the DeepSpeedExamples communication
suite (all_reduce/all_gather/all_to_all/pt2pt sweeps printing algbw/busbw
per size, nccl-tests conventions).  Here the sweep runs in-process over the
mesh's collectives (psum / all_gather / all_to_all / ppermute on a chosen
axis), with the same bandwidth accounting as ``utils/comms_logging.get_bw``
— plus the collectives-engine variants (hierarchical all-reduce, quantized
all-gather/reduce-scatter, 2-hop hierarchical-quantized reduce-scatter)
so the comm trajectory of ``comm_optimizations`` configs is measurable.

    ds_bench                       # sweep all ops over the dp axis
    ds_bench --op quant_all_gather --axis dp --maxsize 28
    ds_bench --mesh dp=4,tp=2      # explicit mesh factorization
    ds_bench --json out.json       # machine-readable rows (BENCH_*.json food)

Prints one table row per (op, size): logical bytes, wire bytes (what the
bottleneck link actually carries — post-quantization payload + scales),
latency, algbw, busbw.  Bandwidths are computed from WIRE bytes.
"""

import argparse
import json
import os
import time

import numpy as np


OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all", "pt2pt")
# collectives-engine variants (comm/collectives/): hierarchy + quantization
ENGINE_OPS = ("hier_all_reduce", "quant_all_gather", "quant_reduce_scatter",
              "hier_quant_reduce_scatter")
ALL_OPS = OPS + ENGINE_OPS

WIRE_FORMAT = "int8"
GROUP_SIZE = 2048


class UnsplittableAxis(ValueError):
    """The axis has no non-trivial (outer, inner) factorization — hier_*
    ops are skipped for it, every other error still fails the bench."""


def _hier(mesh, axis, intra):
    """(smesh, outer_axis, inner_axis, n_out, n_in) for the hier ops: the
    topology layer's split when it can see one, else an even power-of-two
    split so the hierarchical schedule is still measurable on flat/virtual
    meshes (the virtual CPU mesh has no physical topology)."""
    from ..comm.backend import ProcessGroup
    from ..comm.collectives.topology import factor_group
    g = ProcessGroup(mesh, (axis, ))
    h = factor_group(g, intra_node_size=intra)
    if h is not None and len(h.inner_axes) == 1 and len(h.outer_axes) == 1:
        return (h.mesh, h.outer_axes[0], h.inner_axes[0], h.outer_size,
                h.inner_size)
    n = mesh.shape[axis]
    inner = 1
    while inner * inner < n and n % (inner * 2) == 0:
        inner *= 2
    if inner <= 1 or inner >= n:
        # a 1-sized factor on either side is not a hierarchy — measuring it
        # as one would report bogus hier_* rows (e.g. axis size 2)
        raise UnsplittableAxis(
            f"axis {axis!r} (size {n}) has no non-trivial split for "
            "hierarchical ops — pass --intra or use an axis of size ≥ 4")
    from ..comm.collectives.topology import split_mesh
    return (split_mesh(mesh, axis, inner), axis + "_out", axis + "_in",
            n // inner, inner)


def _bench_one(op, axis, nbytes, mesh, iters, warmup, intra=0):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..comm.collectives import quantized as Q

    n = mesh.shape[axis]
    elems = max(n, nbytes // 4 // n * n)  # fp32, divisible by axis size
    x = jnp.arange(elems, dtype=jnp.float32)
    size_bytes = elems * 4
    wire_bytes = size_bytes
    bw_op = op

    def make(fn, m=mesh, in_spec=None, out_spec=None):
        return jax.jit(jax.shard_map(
            fn, mesh=m,
            in_specs=P(axis) if in_spec is None else in_spec,
            out_specs=P(axis) if out_spec is None else out_spec,
            check_vma=False))

    if op == "all_reduce":
        f = make(lambda t: jax.lax.psum(t, axis) / n)
    elif op == "all_gather":
        f = make(lambda t: jax.lax.all_gather(t, axis).reshape(-1)[:t.shape[0]])
    elif op == "reduce_scatter":
        f = make(lambda t: jax.lax.psum_scatter(
            t.reshape(n, -1), axis, scatter_dimension=0,
            tiled=False).reshape(-1))
    elif op == "all_to_all":
        f = make(lambda t: jax.lax.all_to_all(
            t.reshape(n, -1), axis, split_axis=0, concat_axis=0,
            tiled=False).reshape(-1))
    elif op == "pt2pt":
        perm = [(i, (i + 1) % n) for i in range(n)]
        f = make(lambda t: jax.lax.ppermute(t, axis, perm))
        bw_op = "send"
    elif op == "hier_all_reduce":
        from ..comm.collectives.engine import _jit_hier_all_reduce
        from ..comm.reduce_op import ReduceOp
        smesh, out_ax, in_ax, n_out, n_in = _hier(mesh, axis, intra)
        # pad the per-rank block to n_in divisibility via elems choice: elems
        # is divisible by n; require further by n*n_in
        elems = max(n * n_in, elems // (n * n_in) * (n * n_in))
        x = jnp.arange(elems, dtype=jnp.float32)
        size_bytes = elems * 4
        wire_bytes = size_bytes // n_in  # fp payload crossing DCN
        # measure the exact kernel the engine ships, not a re-derivation
        f = _jit_hier_all_reduce(smesh, (in_ax, ), (out_ax, ),
                                 ReduceOp.AVG, n)
        bw_op = "all_reduce"
    elif op == "quant_all_gather":
        f = make(lambda t: Q.quantized_all_gather(
            t, (axis, ), 0, WIRE_FORMAT, GROUP_SIZE).reshape(-1)[:t.shape[0]],
            out_spec=P())
        wire_bytes = Q.quantized_wire_bytes(elems, WIRE_FORMAT, GROUP_SIZE)
        bw_op = "all_gather"
    elif op == "quant_reduce_scatter":
        f = make(lambda t: Q.all_to_all_quant_reduce(
            t, (axis, ), 0, n, wire_format=WIRE_FORMAT,
            group_size=GROUP_SIZE), in_spec=P(), out_spec=P(axis))
        wire_bytes = Q.quantized_wire_bytes(elems, WIRE_FORMAT, GROUP_SIZE)
        bw_op = "reduce_scatter"
    elif op == "hier_quant_reduce_scatter":
        smesh, out_ax, in_ax, n_out, n_in = _hier(mesh, axis, intra)
        f = make(lambda t: Q.hierarchical_quant_reduce_scatter(
            t, (in_ax, ), (out_ax, ), 0, n_in, n_out,
            wire_format=WIRE_FORMAT, group_size=GROUP_SIZE),
            m=smesh, in_spec=P(), out_spec=P((in_ax, out_ax)))
        # quantized payload crossing DCN on 1/n_in of the data
        wire_bytes = Q.quantized_wire_bytes(elems // n_in, WIRE_FORMAT,
                                            GROUP_SIZE)
        bw_op = "reduce_scatter"
    else:
        raise ValueError(op)

    for _ in range(warmup):
        out = f(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(x)
    jax.block_until_ready(out)
    lat = (time.perf_counter() - t0) / iters

    from ..utils.comms_logging import calc_bw_log
    algbw, busbw = calc_bw_log(bw_op, wire_bytes, lat, n)
    return size_bytes, wire_bytes, lat, algbw, busbw


# engine-variant op → (facade op, comms-logging variant tag) so traced
# sweeps use the same ``op[variant]`` vocabulary as training traces
_TRACE_VARIANTS = {
    "hier_all_reduce": ("all_reduce", "hier"),
    "quant_all_gather": ("all_gather", f"q_{WIRE_FORMAT}"),
    "quant_reduce_scatter": ("reduce_scatter", f"q_{WIRE_FORMAT}"),
    "hier_quant_reduce_scatter": ("reduce_scatter", f"hier_q_{WIRE_FORMAT}"),
}


def run(ops=ALL_OPS, axis="dp", minsize=16, maxsize=26, mesh_spec=None,
        iters=20, warmup=3, print_fn=print, intra=0, json_path=None,
        trace_dir=None):
    """Sweep collectives over powers-of-two message sizes.  Returns rows of
    (op, bytes, wire_bytes, latency_s, algbw_gbps, busbw_gbps); with
    ``json_path``, also writes them as machine-readable JSON; with
    ``trace_dir``, archives telemetry artifacts (chrome trace + per-variant
    comm attribution) alongside the sweep output so a BENCH_*.json row can
    be traced back to what actually ran."""
    from ..utils import groups
    if mesh_spec:
        kw = {}
        for part in mesh_spec.split(","):
            k, v = part.split("=")
            kw[k] = int(v)
        groups.reset_mesh()
        groups.initialize_mesh(**kw)
    mesh = groups.get_mesh_state().mesh
    if mesh.shape.get(axis, 1) < 2:
        raise SystemExit(
            f"axis {axis!r} has size {mesh.shape.get(axis, 1)} on mesh "
            f"{dict(mesh.shape)} — nothing to benchmark (pass --mesh)")
    recorder = None
    if trace_dir:
        from ..telemetry import TraceRecorder
        recorder = TraceRecorder(trace_dir, rank=0)
    rows = []
    print_fn(f"# mesh={dict(mesh.shape)} axis={axis} dtype=fp32 "
             f"wire={WIRE_FORMAT}")
    print_fn(f"{'op':<28}{'bytes':>12}{'wire_bytes':>12}{'latency_us':>14}"
             f"{'algbw_Gbps':>12}{'busbw_Gbps':>12}")
    for op in ops:
        for p in range(minsize, maxsize + 1, 2):
            try:
                if recorder is not None:
                    with recorder.span(f"{op}/{1 << p}", cat="bench"):
                        size, wire, lat, algbw, busbw = _bench_one(
                            op, axis, 1 << p, mesh, iters, warmup,
                            intra=intra)
                else:
                    size, wire, lat, algbw, busbw = _bench_one(
                        op, axis, 1 << p, mesh, iters, warmup, intra=intra)
            except UnsplittableAxis as e:
                # hier_* on an unsplittable axis: note and keep sweeping the
                # other ops (any other error still fails the bench loudly)
                print_fn(f"# {op}: skipped ({e})")
                break
            rows.append((op, size, wire, lat, algbw, busbw))
            if recorder is not None:
                base, variant = _TRACE_VARIANTS.get(op, (op, None))
                recorder.comm_event(base, variant, size, wire, lat,
                                    world_size=mesh.shape[axis])
            print_fn(f"{op:<28}{size:>12}{wire:>12}{lat * 1e6:>14.1f}"
                     f"{algbw:>12.2f}{busbw:>12.2f}")
    if json_path:
        payload = {
            "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
            "axis": axis,
            "dtype": "fp32",
            "wire_format": WIRE_FORMAT,
            "quantization_group_size": GROUP_SIZE,
            "rows": [{"op": op, "bytes": int(size), "wire_bytes": int(wire),
                      "latency_us": lat * 1e6, "algbw_gbps": algbw,
                      "busbw_gbps": busbw}
                     for op, size, wire, lat, algbw, busbw in rows],
        }
        with open(json_path, "w") as fh:
            json.dump(payload, fh, indent=2)
        print_fn(f"# wrote {len(rows)} rows to {json_path}")
    if recorder is not None:
        summary_path = os.path.join(recorder.trace_dir, "comm_summary.json")
        with open(summary_path, "w") as fh:
            json.dump({"mesh": {k: int(v)
                                for k, v in dict(mesh.shape).items()},
                       "axis": axis, "ops": recorder.comm_summary()},
                      fh, indent=2)
        recorder.close()
        print_fn(f"# archived trace + comm attribution under "
                 f"{recorder.trace_dir}")
    return rows


def cli_main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds_bench", description="collective micro-benchmarks over the "
        "device mesh (reference bin/ds_bench), incl. hierarchical/quantized "
        "engine variants")
    ap.add_argument("--op", choices=ALL_OPS, default=None,
                    help="single op (default: all)")
    ap.add_argument("--axis", default="dp")
    ap.add_argument("--mesh", default=None,
                    help="mesh factorization, e.g. dp=4,tp=2")
    ap.add_argument("--minsize", type=int, default=16,
                    help="log2 of smallest message (default 16 = 64KiB)")
    ap.add_argument("--maxsize", type=int, default=26,
                    help="log2 of largest message (default 26 = 64MiB)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--intra", type=int, default=0,
                    help="intra-node size for hier_* ops (0 = topology "
                    "auto-detect, falling back to an even split)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="also write machine-readable rows to PATH")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="archive telemetry artifacts (chrome trace + "
                    "per-variant comm attribution) under DIR alongside "
                    "the --json rows")
    args = ap.parse_args(argv)
    run(ops=(args.op, ) if args.op else ALL_OPS, axis=args.axis,
        minsize=args.minsize, maxsize=args.maxsize, mesh_spec=args.mesh,
        iters=args.iters, warmup=args.warmup, intra=args.intra,
        json_path=args.json, trace_dir=args.trace)


if __name__ == "__main__":
    cli_main()
