"""Collective micro-benchmark — the ``ds_bench`` CLI.

Reference: ``bin/ds_bench`` forwards to the DeepSpeedExamples communication
suite (all_reduce/all_gather/all_to_all/pt2pt sweeps printing algbw/busbw
per size, nccl-tests conventions).  Here the sweep runs in-process over the
mesh's collectives (psum / all_gather / all_to_all / ppermute on a chosen
axis), with the same bandwidth accounting as ``utils/comms_logging.get_bw``.

    ds_bench                       # sweep all ops over the dp axis
    ds_bench --op all_reduce --axis dp --maxsize 28
    ds_bench --mesh dp=4,tp=2      # explicit mesh factorization

Prints one table row per (op, size): latency, algbw, busbw.
"""

import argparse
import time

import numpy as np


OPS = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all", "pt2pt")


def _bench_one(op, axis, nbytes, mesh, iters, warmup):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    elems = max(n, nbytes // 4 // n * n)  # fp32, divisible by axis size
    x = jnp.arange(elems, dtype=jnp.float32)

    def make(fn):
        return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=P(axis),
                                     out_specs=P(axis), check_vma=False))

    if op == "all_reduce":
        f = make(lambda t: jax.lax.psum(t, axis) / n)
    elif op == "all_gather":
        f = make(lambda t: jax.lax.all_gather(t, axis).reshape(-1)[:t.shape[0]])
    elif op == "reduce_scatter":
        f = make(lambda t: jax.lax.psum_scatter(
            t.reshape(n, -1), axis, scatter_dimension=0,
            tiled=False).reshape(-1))
    elif op == "all_to_all":
        f = make(lambda t: jax.lax.all_to_all(
            t.reshape(n, -1), axis, split_axis=0, concat_axis=0,
            tiled=False).reshape(-1))
    elif op == "pt2pt":
        perm = [(i, (i + 1) % n) for i in range(n)]
        f = make(lambda t: jax.lax.ppermute(t, axis, perm))
    else:
        raise ValueError(op)

    for _ in range(warmup):
        out = f(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(x)
    jax.block_until_ready(out)
    lat = (time.perf_counter() - t0) / iters

    from ..utils.comms_logging import calc_bw_log
    size_bytes = elems * 4
    algbw, busbw = calc_bw_log(op if op != "pt2pt" else "send", size_bytes,
                               lat, n)
    return size_bytes, lat, algbw, busbw


def run(ops=OPS, axis="dp", minsize=16, maxsize=26, mesh_spec=None,
        iters=20, warmup=3, print_fn=print):
    """Sweep collectives over powers-of-two message sizes.  Returns rows of
    (op, bytes, latency_s, algbw_gbps, busbw_gbps)."""
    from ..utils import groups
    if mesh_spec:
        kw = {}
        for part in mesh_spec.split(","):
            k, v = part.split("=")
            kw[k] = int(v)
        groups.reset_mesh()
        groups.initialize_mesh(**kw)
    mesh = groups.get_mesh_state().mesh
    if mesh.shape.get(axis, 1) < 2:
        raise SystemExit(
            f"axis {axis!r} has size {mesh.shape.get(axis, 1)} on mesh "
            f"{dict(mesh.shape)} — nothing to benchmark (pass --mesh)")
    rows = []
    print_fn(f"# mesh={dict(mesh.shape)} axis={axis} dtype=fp32")
    print_fn(f"{'op':<16}{'bytes':>12}{'latency_us':>14}"
             f"{'algbw_Gbps':>12}{'busbw_Gbps':>12}")
    for op in ops:
        for p in range(minsize, maxsize + 1, 2):
            size, lat, algbw, busbw = _bench_one(
                op, axis, 1 << p, mesh, iters, warmup)
            rows.append((op, size, lat, algbw, busbw))
            print_fn(f"{op:<16}{size:>12}{lat * 1e6:>14.1f}"
                     f"{algbw:>12.2f}{busbw:>12.2f}")
    return rows


def cli_main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ds_bench", description="collective micro-benchmarks over the "
        "device mesh (reference bin/ds_bench)")
    ap.add_argument("--op", choices=OPS, default=None,
                    help="single op (default: all)")
    ap.add_argument("--axis", default="dp")
    ap.add_argument("--mesh", default=None,
                    help="mesh factorization, e.g. dp=4,tp=2")
    ap.add_argument("--minsize", type=int, default=16,
                    help="log2 of smallest message (default 16 = 64KiB)")
    ap.add_argument("--maxsize", type=int, default=26,
                    help="log2 of largest message (default 26 = 64MiB)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    args = ap.parse_args(argv)
    run(ops=(args.op, ) if args.op else OPS, axis=args.axis,
        minsize=args.minsize, maxsize=args.maxsize, mesh_spec=args.mesh,
        iters=args.iters, warmup=args.warmup)


if __name__ == "__main__":
    cli_main()
